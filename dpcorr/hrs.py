"""HRS real-data pipeline (reference real-data-sims.R, components #25-#34).

BMI-vs-Age DP correlation on wave 2 of the HRS long panel:

1. ingest via the framework's RDS reader (real-data-sims.R:13);
2. per-wave missingness summary (:16-33);
3. wave-2 complete-case extraction (:38-41);
4. central-DP standardization of both variables + λ bounds from the private
   moments (:273-287);
5. point estimates — NI clipped-batch with λ overrides + randomized batches,
   and INT with AGE as sender (:290-323);
6. ε-sweep: for each ε in a grid, R Monte-Carlo replications of both
   estimators (:342-448). The reference runs these 9,200 estimator calls
   serially in R; here the ENTIRE grid is served by two compiled
   ``jit(vmap)`` kernels (one NI, one INT — r05): ε is a traced scalar,
   the ε-dependent batch geometry (m, k) becomes in-kernel masked data
   (``correlation_ni_subg(dynamic_geometry=True)``), and the protocol
   direction is named explicitly (``sender="x"``), so no per-ε
   recompile exists to hide (PERFORMANCE.md §ε-sweep: 11× on CPU).

Everything below the ingest boundary is pure JAX on device; only the
column extraction and the final pandas summaries run on host.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from dpcorr.io.rds import read_rds_table
from dpcorr.models.estimators import ci_int_subg, correlation_ni_subg
from dpcorr.obs import trace as obs_trace
from dpcorr.ops.lambdas import lambda_from_priv, lambda_receiver_from_noise
from dpcorr.ops.standardize import dp_sd, standardize_dp
from dpcorr.utils import rng

DEFAULT_PANEL = "/root/reference/hrs_long_panel.rds"


@dataclasses.dataclass(frozen=True)
class HrsConfig:
    """Typed replacement for the reference's script globals
    (real-data-sims.R:260-270)."""

    panel_path: str = DEFAULT_PANEL
    wave: str = "2"
    age_lo: float = 45.0
    age_hi: float = 90.0
    bmi_lo: float = 15.0
    bmi_hi: float = 35.0
    eps_mean: float = 0.10
    eps_m2: float = 0.10
    eps_corr: float = 2.00
    alpha: float = 0.05
    seed: int = rng.MASTER_SEED
    mixquant_mode: str = "det"


# ---------------------------------------------------------------- ingest ----
def load_panel(path: str = DEFAULT_PANEL) -> Mapping:
    """Read the HRS long panel (723,744 × 8; SURVEY.md Appendix B)."""
    return read_rds_table(path)


def wave_missingness(cols: Mapping) -> pd.DataFrame:
    """Per-wave n / missing-age / missing-bmi / complete-case counts
    (real-data-sims.R:16-33)."""
    wave = np.asarray(cols["wave"].values, dtype=object)
    age = cols["agey_e"].values
    bmi = cols["bmi"].values
    rows = []
    for w in sorted(set(wave.tolist()), key=lambda s: int(s)):
        m = wave == w
        a_miss = np.isnan(age[m])
        b_miss = np.isnan(bmi[m])
        rows.append({
            "wave": int(w), "n": int(m.sum()),
            "missing_age": int(a_miss.sum()),
            "missing_bmi": int(b_miss.sum()),
            "complete": int((~a_miss & ~b_miss).sum()),
        })
    return pd.DataFrame(rows)


def extract_wave(cols: Mapping, wave: str = "2"):
    """Complete-case (hhidpn, age, bmi) for one wave
    (real-data-sims.R:38-41). NA removal is host-side, before any kernel."""
    m = np.asarray(cols["wave"].values, dtype=object) == wave
    age = cols["agey_e"].values[m]
    bmi = cols["bmi"].values[m]
    ids = cols["hhidpn"].values[m]
    ok = ~np.isnan(age) & ~np.isnan(bmi)
    return ids[ok], age[ok].astype(np.float32), bmi[ok].astype(np.float32)


# --------------------------------------------------------- standardization ----
@dataclasses.dataclass(frozen=True)
class Standardized:
    """Private standardization output: z-scores, private moments, λ bounds."""

    age_z: jax.Array
    bmi_z: jax.Array
    age_mean: float
    age_sd: float
    bmi_mean: float
    bmi_sd: float
    lam_age: float
    lam_bmi: float
    rho_np: float  # non-private baseline on the standardized data (:349)


@partial(jax.jit, static_argnums=(3,))
def _standardize_kernel(key, age, bmi, cfg: HrsConfig):
    a_mu, a_sd = dp_sd(rng.stream(key, "hrs/std/age"), age,
                       cfg.age_lo, cfg.age_hi, cfg.eps_mean, cfg.eps_m2)
    b_mu, b_sd = dp_sd(rng.stream(key, "hrs/std/bmi"), bmi,
                       cfg.bmi_lo, cfg.bmi_hi, cfg.eps_mean, cfg.eps_m2)
    age_z = standardize_dp(age, a_mu, a_sd, cfg.age_lo, cfg.age_hi)
    bmi_z = standardize_dp(bmi, b_mu, b_sd, cfg.bmi_lo, cfg.bmi_hi)
    corr = jnp.corrcoef(age_z, bmi_z)[0, 1]
    return age_z, bmi_z, a_mu, a_sd, b_mu, b_sd, corr


def standardize(age: np.ndarray, bmi: np.ndarray, cfg: HrsConfig,
                key=None) -> Standardized:
    """DP standardize both variables and derive λ bounds
    (real-data-sims.R:273-287)."""
    if key is None:
        key = rng.master_key(cfg.seed)
    age_z, bmi_z, a_mu, a_sd, b_mu, b_sd, corr = _standardize_kernel(
        key, jnp.asarray(age), jnp.asarray(bmi), cfg)
    a_mu, a_sd, b_mu, b_sd = (float(v) for v in (a_mu, a_sd, b_mu, b_sd))
    return Standardized(
        age_z=age_z, bmi_z=bmi_z,
        age_mean=a_mu, age_sd=a_sd, bmi_mean=b_mu, bmi_sd=b_sd,
        lam_age=float(lambda_from_priv(cfg.age_lo, cfg.age_hi, a_mu, a_sd)),
        lam_bmi=float(lambda_from_priv(cfg.bmi_lo, cfg.bmi_hi, b_mu, b_sd)),
        rho_np=float(corr),
    )


# ------------------------------------------------------------- estimators ----
def _ni_once(key, age_z, bmi_z, eps, lam_age, lam_bmi, alpha):
    """One NI run at privacy ε: λ-override, randomized-batch variant
    (real-data-sims.R:355-372)."""
    return correlation_ni_subg(key, age_z, bmi_z, eps, eps, alpha=alpha,
                               lambda_x=lam_age, lambda_y=lam_bmi,
                               randomize_batches=True, enforce_min_k=True)


def _int_once(key, age_z, bmi_z, eps, lam_age, lam_bmi, lam_recv, delta,
              alpha, mixquant_mode):
    """One INT run at ε, AGE as sender (real-data-sims.R:374-404).

    ``eps1 = eps2 = ε`` makes the sender-selection tie break to X = age,
    matching the reference's explicit AGE→BMI direction.
    """
    return ci_int_subg(key, age_z, bmi_z, eps, eps, alpha=alpha,
                       variant="real", lambda_sender=lam_age,
                       lambda_other=lam_bmi, lambda_receiver=lam_recv,
                       delta_clip=delta, mixquant_mode=mixquant_mode)


@dataclasses.dataclass
class HrsPointResult:
    ni: dict
    int_: dict
    std: Standardized
    n: int
    config: HrsConfig


def point_estimates(cfg: HrsConfig = HrsConfig(), cols=None) -> HrsPointResult:
    """The headline HRS numbers (real-data-sims.R:259-333): one NI and one
    INT (AGE→BMI) estimate at ε_corr on the privately standardized data."""
    cols = load_panel(cfg.panel_path) if cols is None else cols
    _, age, bmi = extract_wave(cols, cfg.wave)
    std = standardize(age, bmi, cfg)
    n = int(age.shape[0])
    delta = 1.0 / n
    lam_recv = float(lambda_receiver_from_noise(std.lam_age, std.lam_bmi,
                                                cfg.eps_corr, delta))
    key = rng.master_key(cfg.seed)
    ni = _ni_once(rng.stream(key, "hrs/ni"), std.age_z, std.bmi_z,
                  cfg.eps_corr, std.lam_age, std.lam_bmi, cfg.alpha)
    it = _int_once(rng.stream(key, "hrs/int"), std.age_z, std.bmi_z,
                   cfg.eps_corr, std.lam_age, std.lam_bmi, lam_recv, delta,
                   cfg.alpha, cfg.mixquant_mode)
    def as_dict(r):
        out = {"rho_hat": float(r.rho_hat), "ci_low": float(r.ci_low),
               "ci_high": float(r.ci_high)}
        if r.aux:  # λ/geometry block (real-data-sims.R:141-147, 244-252)
            out.update({k: float(v) for k, v in r.aux.items()})
        return out

    return HrsPointResult(as_dict(ni), as_dict(it), std, n, cfg)


# --------------------------------------------------------------- ε-sweep ----
# ONE compiled kernel per method serves the ENTIRE ε grid (r05): ε, the
# λs and δ are traced scalars — the NI batch geometry becomes in-kernel
# data via the masked dynamic-geometry estimator, and the INT direction
# is named explicitly (sender="x" = AGE, the reference's AGE→BMI) so no
# Python branch needs a concrete ε. The r04 design compiled one fused
# kernel per ε (23 compiles ≈ 75 s of a 23-ε CPU sweep at small reps);
# this compiles twice, total, for any grid size.
@partial(jax.jit, static_argnums=(5, 6))
def _sweep_ni_kernel(keys_ni, arrays, eps, lam_age, lam_bmi, alpha: float,
                     k_pad: int | None = None):
    age_z, bmi_z = arrays

    def ni(k):
        r = correlation_ni_subg(k, age_z, bmi_z, eps, eps, alpha=alpha,
                                lambda_x=lam_age, lambda_y=lam_bmi,
                                randomize_batches=True, enforce_min_k=True,
                                dynamic_geometry=True, k_pad=k_pad)
        return r.rho_hat, r.ci_low, r.ci_high

    return jax.vmap(ni)(keys_ni)


@partial(jax.jit, static_argnums=(7, 8))
def _sweep_int_kernel(keys_int, arrays, eps, lam_age, lam_bmi, lam_recv,
                      delta, mixquant_mode: str, alpha: float):
    age_z, bmi_z = arrays

    def it(k):
        r = ci_int_subg(k, age_z, bmi_z, eps, eps, alpha=alpha,
                        variant="real", lambda_sender=lam_age,
                        lambda_other=lam_bmi, lambda_receiver=lam_recv,
                        delta_clip=delta, mixquant_mode=mixquant_mode,
                        sender="x")
        return r.rho_hat, r.ci_low, r.ci_high

    return jax.vmap(it)(keys_int)


def eps_sweep(cfg: HrsConfig = HrsConfig(), cols=None,
              eps_grid=None, reps: int = 200,
              progress: bool = False) -> pd.DataFrame:
    """The ε-sweep (real-data-sims.R:342-448): per-ε mean estimates, mean CI
    ends, and CI-end quantiles (q10 of lows, q90 of highs) for NI and INT.

    Returns the per-ε summary frame the figures consume; the raw per-rep
    table is attached as ``.attrs["runs"]`` (note: pandas serializes
    ``attrs`` into parquet metadata, so persist the two frames separately
    — ``summ.attrs["runs"].to_parquet(...)`` and a plain-attrs copy of the
    summary — rather than calling ``summ.to_parquet`` directly).
    """
    cols = load_panel(cfg.panel_path) if cols is None else cols
    _, age, bmi = extract_wave(cols, cfg.wave)
    std = standardize(age, bmi, cfg)
    n = int(age.shape[0])
    delta = 1.0 / n
    if eps_grid is None:
        eps_grid = np.round(np.arange(0.25, 2.5001, 0.1), 10)  # 23 values

    master = rng.master_key(cfg.seed)
    arrays = (std.age_z, std.bmi_z)

    # Two compiles serve the whole grid (see the kernel comment above):
    # ε enters as a traced scalar, so dispatching the grid is 2·|grid|
    # launches of the same two compiled programs — no per-ε compile, no
    # compile/execute pipelining needed (the r04 dispatch-ahead design
    # existed to hide 23 per-ε compiles; real-data-sims.R:345-448 is
    # fully serial). receiver λs fetched BEFORE the first dispatch:
    # float() of a device value after a dispatch would queue behind the
    # in-flight sweep kernel and serialize the pipeline.
    lam_recvs = [float(lambda_receiver_from_noise(std.lam_age, std.lam_bmi,
                                                  float(e), delta))
                 for e in eps_grid]
    from dpcorr.models.estimators.common import (k_pad_for,
                                                 warn_f32_geometry_band_once)

    # the sweep traces ε through the f32 geometry rule; flag (once) any
    # grid value in the ~1e-6 band where f32 and f64 pick adjacent m
    warn_f32_geometry_band_once([(float(e), float(e)) for e in eps_grid],
                                n=n, where="hrs.eps_sweep")
    k_pad = k_pad_for(n, [float(e) * float(e) for e in eps_grid])
    # span model mirrors the grid driver's: one hrs.eps_sweep root, a
    # dispatch child per ε in phase 1 and a fetch child per ε in phase 2
    # (explicit parent= so the two loops need no thread-local stack)
    tr = obs_trace.tracer()
    root = tr.start_span("hrs.eps_sweep", n=n, n_eps=len(eps_grid),
                         reps=reps)
    try:
        pending = []
        for eps_idx, eps in enumerate(eps_grid):
            eps = float(eps)
            dsp = tr.start_span("hrs.dispatch", parent=root, eps=eps)
            try:
                # per-(method, ε, rep) keys — the key-tree analogue of the
                # reference's seed formulas 10+37·rep+1000·eps_idx /
                # 20+41·rep+...
                k_eps = rng.design_key(master, eps_idx)
                keys_ni = rng.rep_keys(rng.stream(k_eps, "hrs/sweep/ni"),
                                       reps)
                keys_int = rng.rep_keys(rng.stream(k_eps, "hrs/sweep/int"),
                                        reps)
                if progress:
                    print(f"eps={eps:.2f}: dispatched "
                          f"({eps_idx + 1}/{len(eps_grid)})", flush=True)
                eps_t = jnp.float32(eps)
                pending.append((eps, (
                    _sweep_ni_kernel(keys_ni, arrays, eps_t, std.lam_age,
                                     std.lam_bmi, cfg.alpha, k_pad),
                    _sweep_int_kernel(keys_int, arrays, eps_t, std.lam_age,
                                      std.lam_bmi,
                                      jnp.float32(lam_recvs[eps_idx]),
                                      jnp.float32(delta), cfg.mixquant_mode,
                                      cfg.alpha))))
            finally:
                dsp.end()

        runs = []
        for eps, out in pending:
            fsp = tr.start_span("hrs.fetch", parent=root, eps=eps)
            try:
                (ni_hat, ni_lo, ni_hi), (int_hat, int_lo, int_hi) = \
                    jax.tree.map(np.asarray, out)
            finally:
                fsp.end()
            for meth, hat, lo, hi in (("NI", ni_hat, ni_lo, ni_hi),
                                      ("INT", int_hat, int_lo, int_hi)):
                runs.append(pd.DataFrame({
                    "method": meth, "eps_corr": eps,
                    "rep": np.arange(1, reps + 1),
                    "rho_hat": hat, "ci_low": lo, "ci_high": hi,
                }))
            if progress:
                print(f"eps={eps:.2f}: NI mean {ni_hat.mean():+.4f}, "
                      f"INT mean {int_hat.mean():+.4f}")

        runs_df = pd.concat(runs, ignore_index=True)
        g = runs_df.groupby(["method", "eps_corr"], sort=True)
        summ = pd.DataFrame({
            "rho_hat_mean": g["rho_hat"].mean(),
            "ci_low_mean": g["ci_low"].mean(),
            "ci_high_mean": g["ci_high"].mean(),
            "ci_low_q10": g["ci_low"].quantile(0.10),
            "ci_high_q90": g["ci_high"].quantile(0.90),
        }).reset_index()
        summ.attrs["runs"] = runs_df
        summ.attrs["rho_np"] = std.rho_np
    finally:
        root.end()
    return summ


# -------------------------------------------------------------- bootstrap ----
@partial(jax.jit, static_argnums=(2, 7, 8, 9))
def _bootstrap_kernel(keys, arrays, eps: float, lam_age, lam_bmi, lam_recv,
                      delta, alpha: float, mixquant_mode: str, chunk: int):
    """Row-resampled replications of both estimators at one ε as a chunked
    vmapped kernel: per rep, a with-replacement resample of the standardized
    rows (gathered on device), then the NI + INT pipeline on the resample.

    This is the uncertainty quantification the reference *lacks* (its sweep
    replicates only the DP noise on fixed data, real-data-sims.R:342-448);
    BASELINE.md config 4 asks for 10k of these.
    """
    from dpcorr.sim import chunked_vmap

    age_z, bmi_z = arrays
    n = age_z.shape[0]

    def one(k):
        idx = jax.random.choice(rng.stream(k, "hrs/boot/idx"), n, (n,),
                                replace=True)
        a, b = age_z[idx], bmi_z[idx]
        ni = _ni_once(rng.stream(k, "hrs/boot/ni"), a, b, eps, lam_age,
                      lam_bmi, alpha)
        it = _int_once(rng.stream(k, "hrs/boot/int"), a, b, eps, lam_age,
                       lam_bmi, lam_recv, delta, alpha, mixquant_mode)
        return (ni.rho_hat, ni.ci_low, ni.ci_high,
                it.rho_hat, it.ci_low, it.ci_high)

    return chunked_vmap(one, keys, chunk)


def bootstrap(cfg: HrsConfig = HrsConfig(), cols=None, reps: int = 10_000,
              eps: float | None = None, chunk: int = 64) -> pd.DataFrame:
    """``reps`` bootstrap replications (row resampling + fresh DP noise) of
    the headline HRS estimates at privacy ``eps`` (default ε_corr).

    Returns the per-rep frame; summary quantiles in ``.attrs["summary"]``.
    """
    cols = load_panel(cfg.panel_path) if cols is None else cols
    _, age, bmi = extract_wave(cols, cfg.wave)
    std = standardize(age, bmi, cfg)
    n = int(age.shape[0])
    eps = cfg.eps_corr if eps is None else float(eps)
    delta = 1.0 / n
    lam_recv = float(lambda_receiver_from_noise(std.lam_age, std.lam_bmi,
                                                eps, delta))
    keys = rng.rep_keys(rng.stream(rng.master_key(cfg.seed), "hrs/boot"), reps)
    out = jax.tree.map(np.asarray, _bootstrap_kernel(
        keys, (std.age_z, std.bmi_z), eps, std.lam_age, std.lam_bmi,
        lam_recv, delta, cfg.alpha, cfg.mixquant_mode, chunk))
    df = pd.DataFrame(dict(zip(
        ("ni_hat", "ni_low", "ni_high", "int_hat", "int_low", "int_high"),
        out, strict=True)))
    df.attrs["rho_np"] = std.rho_np
    df.attrs["summary"] = {
        meth: {
            "mean": float(df[f"{meth}_hat"].mean()),
            "sd": float(df[f"{meth}_hat"].std(ddof=1)),
            "q025": float(df[f"{meth}_hat"].quantile(0.025)),
            "q975": float(df[f"{meth}_hat"].quantile(0.975)),
        }
        for meth in ("ni", "int")
    }
    return df
