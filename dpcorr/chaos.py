"""Deterministic crash-point chaos: named kill sites, seeded plans.

PR 5's fault injector perturbs the *wire* (drop/delay/duplicate); this
module injects the harder failure class — the process dies at a chosen
instruction boundary. Recovery code is only trustworthy if every crash
window it claims to survive is actually exercised, so the windows are
named: code that has a durability boundary calls
``chaos.point("gate.post_charge")`` at the boundary, and a *plan*
(installed from the CLI, the ``DPCORR_CHAOS`` env var, or a test) kills
the process on a chosen traversal of a chosen point.

Design constraints, in order:

- **Deterministic and reproducible.** A plan is fully described by
  ``(point, hit, mode)`` or by a single integer seed that derives them
  (stdlib ``random.Random`` over the static :data:`MATRIX_POINTS`
  list — the jax key tree is never touched, so chaos can never perturb
  estimator noise). The party runtime records the active plan in its
  transcript header; re-running with that seed reproduces the same
  crash at the same step.
- **Honest kills.** The default mode ``exit`` is ``os._exit(42)`` — no
  ``finally`` blocks, no atexit, no flushes — the closest a test can
  get to SIGKILL from inside the victim. Mode ``raise`` throws
  :class:`SimulatedCrash` (a ``BaseException``, so transport-failure
  handlers like the gate's refund path do NOT treat it as a delivery
  failure) for fast in-process resume tests.
- **Near-zero cost when off.** ``point()`` is one global ``is None``
  check when no plan is installed — it is called from the ledger's
  charge path and the coalescer's flush loop.

jax-free and import-light on purpose: the ledger, gate, party and
coalescer all import this module, including under jax-free CLI paths.
"""

from __future__ import annotations

import os
import random
import threading
import time

#: Exit status a chaos kill dies with — the restart driver asserts on
#: it so an ordinary crash (bug, OOM) is never mistaken for the plan.
EXIT_CODE = 42

#: Every registered crash point. Static, ordered, and append-only by
#: convention: seed-derived plans index into this list, so reordering
#: would silently change what historical seeds reproduce.
KNOWN_POINTS = (
    # protocol session (party.py / gate.py / journal consumers)
    "party.post_handshake",   # handshake done, nothing journaled yet
    "journal.post_prepare",   # outbound slot durable, not charged/sent
    "gate.post_charge",       # eps durably charged, release not sent
    "gate.post_send",         # release acked, journal not marked
    "party.post_gated",       # journal marked acked, transcript pending
    # ledger durability windows (serve/ledger.py; also traversed by the
    # protocol parties — the gate charges the same ledger)
    "ledger.pre_persist",     # spend mutated in memory, file untouched
    "ledger.post_persist",    # spend on disk, audit event not written
    # serve flush pipeline (serve/coalescer.py)
    "coalescer.pre_flush",    # batch popped, kernel not dispatched
    "coalescer.post_flush",   # responses resolved, stats published
    # budget-directory persist windows (serve/budget_dir.py) — every
    # durability boundary of a sharded per-user charge
    "budget.pre_journal",     # admitted, WAL line not yet appended
    "budget.post_journal",    # WAL line fsynced, not applied in memory
    "budget.mid_compaction",  # snapshot gen+1 renamed, WAL still gen
    "budget.mid_eviction",    # cold spill appended, user still resident
    # federation matrix sessions (protocol/federation.py)
    "federation.pre_release",  # column artifacts built, round not charged
    "federation.mid_matrix",   # some pair links finished, others pending
    "federation.pre_finish",   # round validated, finish kernel not run
    # stream window release sequence (stream/service.py) — NOT in
    # MATRIX_POINTS: the two-party chaos matrix never traverses them;
    # benchmarks/stream_load.py and the CI stream-smoke job do
    "stream.pre_release",      # window closable, nothing charged yet
    "stream.mid_window",       # ingest batch in the WAL, not acked
    "stream.post_journal",     # release journaled, window not closed
    # fleet lease takeover (serve/fleet/lease.py) — NOT in
    # MATRIX_POINTS: the two-party chaos matrix never traverses it;
    # tests/test_fleet_serve.py and the fleet-scale CI job do
    "fleet.pre_lease_commit",  # claim file won, lease not committed
)

#: The step-kill matrix `dpcorr chaos` sweeps: the points every protocol
#: role traverses exactly once per session (the ledger windows fire
#: inside the role's own gated charge). The coalescer points are serve-
#: side and are exercised by the serve/ledger crash tests instead.
MATRIX_POINTS = (
    "party.post_handshake",
    "journal.post_prepare",
    "gate.post_charge",
    "ledger.post_persist",
    "gate.post_send",
    "party.post_gated",
    # budget-directory windows: traversed once per gated charge when
    # the party wraps its ledger in a CompositeLedger (the chaos driver
    # arms the directory with compact-every=1 / max-resident=0 so the
    # compaction and eviction windows fire on that same charge)
    "budget.pre_journal",
    "budget.post_journal",
    "budget.mid_compaction",
    "budget.mid_eviction",
    # federation points: two-party sessions never traverse these; the
    # chaos CLI routes them to a 3-party matrix case instead (and the
    # two-party crash-resume matrix test filters them out)
    "federation.pre_release",
    "federation.mid_matrix",
    "federation.pre_finish",
)

_MODES = ("exit", "raise")
_KNOWN = frozenset(KNOWN_POINTS)


class SimulatedCrash(BaseException):
    """An in-process stand-in for a kill at a chaos point.

    Deliberately a ``BaseException``: recovery handlers catch concrete
    failure types (``TransportError`` → refund, ``Exception`` →
    degrade), and a simulated *crash* must sail through all of them
    exactly like ``os._exit`` would — a refund fired by a pretend kill
    would test a code path no real crash takes.
    """

    def __init__(self, point: str):
        self.point = point
        super().__init__(f"simulated crash at chaos point {point!r}")


class ChaosPlan:
    """One planned kill: die on the ``hit``-th traversal of ``point``.

    ``role`` is driver metadata (which party process receives the plan);
    ``thread_name`` scopes an in-process plan to one victim thread so
    the surviving party thread in a two-threads-one-process test sails
    past the same point untouched. ``seed`` records how the plan was
    derived, for the transcript header.
    """

    def __init__(self, point: str, hit: int = 1, mode: str = "exit",
                 role: str | None = None, seed: int | None = None,
                 thread_name: str | None = None):
        if point not in _KNOWN:
            raise ValueError(f"unknown chaos point {point!r}; "
                             f"registered: {KNOWN_POINTS}")
        if hit < 1:
            raise ValueError(f"hit must be >= 1, got {hit}")
        if mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES}, got {mode!r}")
        self.point = point
        self.hit = int(hit)
        self.mode = mode
        self.role = role
        self.seed = seed
        self.thread_name = thread_name

    def to_dict(self) -> dict:
        """Transcript-header form — everything needed to reproduce."""
        out = {"point": self.point, "hit": self.hit, "mode": self.mode}
        if self.role is not None:
            out["role"] = self.role
        if self.seed is not None:
            out["seed"] = self.seed
        return out

    def to_spec(self) -> str:
        """The ``--chaos``/``DPCORR_CHAOS`` string form of this plan."""
        parts = [f"point={self.point}", f"hit={self.hit}",
                 f"mode={self.mode}"]
        if self.role is not None:
            parts.append(f"role={self.role}")
        return ",".join(parts)


def plan_from_seed(seed: int, mode: str = "exit") -> ChaosPlan:
    """Derive a matrix kill deterministically from one integer: which
    point, which traversal (always the first — each matrix point fires
    once per session) and which role is the victim. stdlib RNG over the
    static matrix, so the same seed reproduces the same kill forever."""
    r = random.Random(int(seed))
    point = r.choice(MATRIX_POINTS)
    role = r.choice(("x", "y"))
    return ChaosPlan(point, hit=1, mode=mode, role=role, seed=int(seed))


def plan_from_spec(spec: str) -> ChaosPlan:
    """Parse ``"point=gate.post_charge,hit=1,mode=exit"`` or
    ``"seed=123"`` (seed-derived matrix kill)."""
    fields: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad chaos spec field {part!r} "
                             "(want key=value)")
        k, v = part.split("=", 1)
        fields[k.strip()] = v.strip()
    if "seed" in fields:
        plan = plan_from_seed(int(fields["seed"]),
                              mode=fields.get("mode", "exit"))
        if "role" in fields:
            plan.role = fields["role"]
        return plan
    if "point" not in fields:
        raise ValueError(f"chaos spec {spec!r} names neither point= "
                         "nor seed=")
    return ChaosPlan(fields["point"], hit=int(fields.get("hit", "1")),
                     mode=fields.get("mode", "exit"),
                     role=fields.get("role"))


def plan_from_env(env: str = "DPCORR_CHAOS") -> ChaosPlan | None:
    """The subprocess hook: a victim process started with
    ``DPCORR_CHAOS=point=...,hit=...`` installs its own kill."""
    spec = os.environ.get(env)
    return plan_from_spec(spec) if spec else None


_lock = threading.Lock()
_plan: ChaosPlan | None = None  # guarded by: _lock
_counts: dict[str, int] = {}  # guarded by: _lock
_crash_hooks: list = []  # guarded by: _lock


def on_crash(fn) -> None:
    """Register ``fn(point_name)`` to run just BEFORE a planned kill
    (both modes — ahead of ``os._exit`` and ahead of the raise). The
    flight recorder's last-gasp dump hook: ``exit`` mode skips every
    ``finally``/atexit on purpose, so anything that must survive the
    kill has to happen here. Hooks are best-effort — an exception in
    one must not save the victim."""
    with _lock:
        if fn not in _crash_hooks:
            _crash_hooks.append(fn)


def remove_crash_hook(fn) -> None:
    with _lock:
        if fn in _crash_hooks:
            _crash_hooks.remove(fn)


def install(plan: ChaosPlan | None) -> None:
    """Arm ``plan`` process-wide (traversal counters reset). ``None``
    disarms — same as :func:`clear`."""
    global _plan
    with _lock:
        _plan = plan
        _counts.clear()


def clear() -> None:
    install(None)


def active() -> ChaosPlan | None:
    # dpcorr-lint: ignore[lock-unguarded-read] — benign stale read (racing disarm)
    return _plan


def point(name: str) -> None:
    """Declare one crash window. No-op unless the armed plan names this
    point (and this thread, for thread-scoped plans); on the planned
    traversal the process dies (``exit``) or :class:`SimulatedCrash`
    propagates (``raise``)."""
    # dpcorr-lint: ignore[lock-unguarded-read] — hot-path probe, re-checked under _lock
    plan = _plan
    if plan is None:
        return
    if name not in _KNOWN:
        raise ValueError(f"unregistered chaos point {name!r}; add it to "
                         "chaos.KNOWN_POINTS")
    if plan.point != name:
        return
    if plan.thread_name is not None \
            and threading.current_thread().name != plan.thread_name:
        return
    with _lock:
        if _plan is not plan:  # disarmed while we raced here
            return
        _counts[name] = _counts.get(name, 0) + 1
        if _counts[name] != plan.hit:
            return
        hooks = list(_crash_hooks)
    for fn in hooks:
        try:
            fn(name)
        except Exception:
            pass  # a broken hook must not save the victim
    if plan.mode == "exit":
        os._exit(EXIT_CODE)
    raise SimulatedCrash(name)


# ------------------------------------------------------------- faults ----
# Crash points (above) model the process DYING at a boundary; fault
# points model it LIMPING — a kernel that raises, a kernel that takes
# 50x its budget, a flush thread that stalls. The overload-resilience
# layer (ISSUE 8: deadlines, shedding, circuit breaker, brownout) is
# only trustworthy if those degradations are actually injectable, so
# they get their own registry with deliberately different semantics:
#
# - ``SimulatedFault`` is a plain ``Exception``. A crash must sail
#   through every handler (BaseException); a fault must be CAUGHT by
#   them — it stands in for "the kernel raised", which is exactly the
#   failure class the breaker and the unbatched fallback exist for.
# - Multiple fault plans may be armed at once (slow kernels AND a
#   stalled flush), and a plan fires on a traversal *range* rather
#   than one hit — sustained degradation, not a single event.
# - ``sleep`` mode delays instead of raising, for latency faults.

#: Registered fault sites. Append-only, same convention as
#: KNOWN_POINTS; disjoint from it — a name is a crash point or a fault
#: point, never both.
FAULT_POINTS = (
    "serve.kernel",        # batched/unbatched launch raises
    "serve.kernel_slow",   # launch takes delay_s longer than it should
    "serve.flush_stall",   # the flush thread stalls before dispatch
)

_FAULT_MODES = ("fail", "sleep")
_KNOWN_FAULTS = frozenset(FAULT_POINTS)


class SimulatedFault(Exception):
    """An injected *service* fault (kernel failure, not process death).

    A plain ``Exception`` on purpose — the degradation machinery under
    test (unbatched fallback, circuit breaker, retrying client) handles
    concrete execution failures, and the injected stand-in must be
    caught exactly like a real lowering error or device OOM would be.
    """

    def __init__(self, point: str):
        self.point = point
        super().__init__(f"simulated fault at chaos point {point!r}")


class FaultPlan:
    """One armed degradation: traversals ``after+1 .. after+times`` of
    ``point`` either raise :class:`SimulatedFault` (``mode="fail"``) or
    sleep ``delay_s`` (``mode="sleep"``). ``times=None`` fires forever
    (until cleared) — sustained overload, the brownout trigger."""

    def __init__(self, point: str, mode: str = "fail",
                 times: int | None = None, delay_s: float = 0.0,
                 after: int = 0):
        if point not in _KNOWN_FAULTS:
            raise ValueError(f"unknown fault point {point!r}; "
                             f"registered: {FAULT_POINTS}")
        if mode not in _FAULT_MODES:
            raise ValueError(f"mode must be one of {_FAULT_MODES}, "
                             f"got {mode!r}")
        if times is not None and times < 1:
            raise ValueError(f"times must be >= 1 or None, got {times}")
        if mode == "sleep" and delay_s <= 0.0:
            raise ValueError("sleep mode needs delay_s > 0")
        if after < 0:
            raise ValueError(f"after must be >= 0, got {after}")
        self.point = point
        self.mode = mode
        self.times = times
        self.delay_s = float(delay_s)
        self.after = int(after)

    def to_dict(self) -> dict:
        out = {"point": self.point, "mode": self.mode}
        if self.times is not None:
            out["times"] = self.times
        if self.delay_s:
            out["delay_s"] = self.delay_s
        if self.after:
            out["after"] = self.after
        return out


def fault_from_spec(spec: str) -> FaultPlan:
    """Parse ``"point=serve.kernel,mode=fail,times=3"`` or
    ``"point=serve.kernel_slow,mode=sleep,delay_ms=40"``."""
    fields: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad fault spec field {part!r} "
                             "(want key=value)")
        k, v = part.split("=", 1)
        fields[k.strip()] = v.strip()
    if "point" not in fields:
        raise ValueError(f"fault spec {spec!r} names no point=")
    delay = float(fields.get("delay_s", "0") or 0)
    if "delay_ms" in fields:
        delay = float(fields["delay_ms"]) / 1e3
    return FaultPlan(fields["point"],
                     mode=fields.get("mode", "fail"),
                     times=(int(fields["times"]) if "times" in fields
                            else None),
                     delay_s=delay,
                     after=int(fields.get("after", "0")))


def faults_from_env(env: str = "DPCORR_FAULTS") -> list[FaultPlan]:
    """``DPCORR_FAULTS`` holds ``;``-separated fault specs — the
    subprocess hook mirroring :func:`plan_from_env`."""
    raw = os.environ.get(env)
    if not raw:
        return []
    return [fault_from_spec(s) for s in raw.split(";") if s.strip()]


_fault_plans: list[FaultPlan] = []  # guarded by: _lock
_fault_counts: dict[int, int] = {}  # guarded by: _lock


def install_fault(plan: FaultPlan) -> None:
    """Arm one fault plan (additive — unlike crash plans, several may
    be live at once)."""
    with _lock:
        _fault_plans.append(plan)


def install_faults(plans: list[FaultPlan]) -> None:
    for p in plans:
        install_fault(p)


def clear_faults() -> None:
    with _lock:
        _fault_plans.clear()
        _fault_counts.clear()


def active_faults() -> list[FaultPlan]:
    with _lock:
        return list(_fault_plans)


def fault(name: str) -> None:
    """Declare one fault site. No-op unless an armed plan names this
    point and the traversal falls in its firing window; then sleep
    (``sleep``) or raise :class:`SimulatedFault` (``fail``)."""
    # dpcorr-lint: ignore[lock-unguarded-read] — hot-path probe, re-read under _lock
    if not _fault_plans:
        return
    if name not in _KNOWN_FAULTS:
        raise ValueError(f"unregistered fault point {name!r}; add it to "
                         "chaos.FAULT_POINTS")
    fire: FaultPlan | None = None
    with _lock:
        for plan in _fault_plans:
            if plan.point != name:
                continue
            k = _fault_counts.get(id(plan), 0) + 1
            _fault_counts[id(plan)] = k
            if k <= plan.after:
                continue
            if plan.times is not None and k > plan.after + plan.times:
                continue
            fire = plan
            break
    if fire is None:
        return
    if fire.mode == "sleep":
        time.sleep(fire.delay_s)
        return
    raise SimulatedFault(name)
