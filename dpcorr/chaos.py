"""Deterministic crash-point chaos: named kill sites, seeded plans.

PR 5's fault injector perturbs the *wire* (drop/delay/duplicate); this
module injects the harder failure class — the process dies at a chosen
instruction boundary. Recovery code is only trustworthy if every crash
window it claims to survive is actually exercised, so the windows are
named: code that has a durability boundary calls
``chaos.point("gate.post_charge")`` at the boundary, and a *plan*
(installed from the CLI, the ``DPCORR_CHAOS`` env var, or a test) kills
the process on a chosen traversal of a chosen point.

Design constraints, in order:

- **Deterministic and reproducible.** A plan is fully described by
  ``(point, hit, mode)`` or by a single integer seed that derives them
  (stdlib ``random.Random`` over the static :data:`MATRIX_POINTS`
  list — the jax key tree is never touched, so chaos can never perturb
  estimator noise). The party runtime records the active plan in its
  transcript header; re-running with that seed reproduces the same
  crash at the same step.
- **Honest kills.** The default mode ``exit`` is ``os._exit(42)`` — no
  ``finally`` blocks, no atexit, no flushes — the closest a test can
  get to SIGKILL from inside the victim. Mode ``raise`` throws
  :class:`SimulatedCrash` (a ``BaseException``, so transport-failure
  handlers like the gate's refund path do NOT treat it as a delivery
  failure) for fast in-process resume tests.
- **Near-zero cost when off.** ``point()`` is one global ``is None``
  check when no plan is installed — it is called from the ledger's
  charge path and the coalescer's flush loop.

jax-free and import-light on purpose: the ledger, gate, party and
coalescer all import this module, including under jax-free CLI paths.
"""

from __future__ import annotations

import os
import random
import threading

#: Exit status a chaos kill dies with — the restart driver asserts on
#: it so an ordinary crash (bug, OOM) is never mistaken for the plan.
EXIT_CODE = 42

#: Every registered crash point. Static, ordered, and append-only by
#: convention: seed-derived plans index into this list, so reordering
#: would silently change what historical seeds reproduce.
KNOWN_POINTS = (
    # protocol session (party.py / gate.py / journal consumers)
    "party.post_handshake",   # handshake done, nothing journaled yet
    "journal.post_prepare",   # outbound slot durable, not charged/sent
    "gate.post_charge",       # eps durably charged, release not sent
    "gate.post_send",         # release acked, journal not marked
    "party.post_gated",       # journal marked acked, transcript pending
    # ledger durability windows (serve/ledger.py; also traversed by the
    # protocol parties — the gate charges the same ledger)
    "ledger.pre_persist",     # spend mutated in memory, file untouched
    "ledger.post_persist",    # spend on disk, audit event not written
    # serve flush pipeline (serve/coalescer.py)
    "coalescer.pre_flush",    # batch popped, kernel not dispatched
    "coalescer.post_flush",   # responses resolved, stats published
)

#: The step-kill matrix `dpcorr chaos` sweeps: the points every protocol
#: role traverses exactly once per session (the ledger windows fire
#: inside the role's own gated charge). The coalescer points are serve-
#: side and are exercised by the serve/ledger crash tests instead.
MATRIX_POINTS = (
    "party.post_handshake",
    "journal.post_prepare",
    "gate.post_charge",
    "ledger.post_persist",
    "gate.post_send",
    "party.post_gated",
)

_MODES = ("exit", "raise")
_KNOWN = frozenset(KNOWN_POINTS)


class SimulatedCrash(BaseException):
    """An in-process stand-in for a kill at a chaos point.

    Deliberately a ``BaseException``: recovery handlers catch concrete
    failure types (``TransportError`` → refund, ``Exception`` →
    degrade), and a simulated *crash* must sail through all of them
    exactly like ``os._exit`` would — a refund fired by a pretend kill
    would test a code path no real crash takes.
    """

    def __init__(self, point: str):
        self.point = point
        super().__init__(f"simulated crash at chaos point {point!r}")


class ChaosPlan:
    """One planned kill: die on the ``hit``-th traversal of ``point``.

    ``role`` is driver metadata (which party process receives the plan);
    ``thread_name`` scopes an in-process plan to one victim thread so
    the surviving party thread in a two-threads-one-process test sails
    past the same point untouched. ``seed`` records how the plan was
    derived, for the transcript header.
    """

    def __init__(self, point: str, hit: int = 1, mode: str = "exit",
                 role: str | None = None, seed: int | None = None,
                 thread_name: str | None = None):
        if point not in _KNOWN:
            raise ValueError(f"unknown chaos point {point!r}; "
                             f"registered: {KNOWN_POINTS}")
        if hit < 1:
            raise ValueError(f"hit must be >= 1, got {hit}")
        if mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES}, got {mode!r}")
        self.point = point
        self.hit = int(hit)
        self.mode = mode
        self.role = role
        self.seed = seed
        self.thread_name = thread_name

    def to_dict(self) -> dict:
        """Transcript-header form — everything needed to reproduce."""
        out = {"point": self.point, "hit": self.hit, "mode": self.mode}
        if self.role is not None:
            out["role"] = self.role
        if self.seed is not None:
            out["seed"] = self.seed
        return out

    def to_spec(self) -> str:
        """The ``--chaos``/``DPCORR_CHAOS`` string form of this plan."""
        parts = [f"point={self.point}", f"hit={self.hit}",
                 f"mode={self.mode}"]
        if self.role is not None:
            parts.append(f"role={self.role}")
        return ",".join(parts)


def plan_from_seed(seed: int, mode: str = "exit") -> ChaosPlan:
    """Derive a matrix kill deterministically from one integer: which
    point, which traversal (always the first — each matrix point fires
    once per session) and which role is the victim. stdlib RNG over the
    static matrix, so the same seed reproduces the same kill forever."""
    r = random.Random(int(seed))
    point = r.choice(MATRIX_POINTS)
    role = r.choice(("x", "y"))
    return ChaosPlan(point, hit=1, mode=mode, role=role, seed=int(seed))


def plan_from_spec(spec: str) -> ChaosPlan:
    """Parse ``"point=gate.post_charge,hit=1,mode=exit"`` or
    ``"seed=123"`` (seed-derived matrix kill)."""
    fields: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad chaos spec field {part!r} "
                             "(want key=value)")
        k, v = part.split("=", 1)
        fields[k.strip()] = v.strip()
    if "seed" in fields:
        plan = plan_from_seed(int(fields["seed"]),
                              mode=fields.get("mode", "exit"))
        if "role" in fields:
            plan.role = fields["role"]
        return plan
    if "point" not in fields:
        raise ValueError(f"chaos spec {spec!r} names neither point= "
                         "nor seed=")
    return ChaosPlan(fields["point"], hit=int(fields.get("hit", "1")),
                     mode=fields.get("mode", "exit"),
                     role=fields.get("role"))


def plan_from_env(env: str = "DPCORR_CHAOS") -> ChaosPlan | None:
    """The subprocess hook: a victim process started with
    ``DPCORR_CHAOS=point=...,hit=...`` installs its own kill."""
    spec = os.environ.get(env)
    return plan_from_spec(spec) if spec else None


_lock = threading.Lock()
_plan: ChaosPlan | None = None
_counts: dict[str, int] = {}


def install(plan: ChaosPlan | None) -> None:
    """Arm ``plan`` process-wide (traversal counters reset). ``None``
    disarms — same as :func:`clear`."""
    global _plan
    with _lock:
        _plan = plan
        _counts.clear()


def clear() -> None:
    install(None)


def active() -> ChaosPlan | None:
    return _plan


def point(name: str) -> None:
    """Declare one crash window. No-op unless the armed plan names this
    point (and this thread, for thread-scoped plans); on the planned
    traversal the process dies (``exit``) or :class:`SimulatedCrash`
    propagates (``raise``)."""
    plan = _plan
    if plan is None:
        return
    if name not in _KNOWN:
        raise ValueError(f"unregistered chaos point {name!r}; add it to "
                         "chaos.KNOWN_POINTS")
    if plan.point != name:
        return
    if plan.thread_name is not None \
            and threading.current_thread().name != plan.thread_name:
        return
    with _lock:
        if _plan is not plan:  # disarmed while we raced here
            return
        _counts[name] = _counts.get(name, 0) + 1
        if _counts[name] != plan.hit:
            return
    if plan.mode == "exit":
        os._exit(EXIT_CODE)
    raise SimulatedCrash(name)
