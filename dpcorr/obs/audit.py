"""Privacy-budget audit trail: every ledger mutation as a structured event.

The ledger (serve.ledger) persists only the *current* spend table — the
correct recovery artifact, but useless for the questions an auditor or
an on-call operator actually asks: *which request* spent party A to
exhaustion, *when* did refusals start, what was the ε timeline. This
module is the event log answering those:

- every **charge**, **refund** and **refusal** is appended as one JSON
  line carrying the per-party ε deltas, the wall timestamp, a
  monotonically increasing sequence number, and — when the serve layer
  is traced — the originating request's ``trace_id``, so one budget
  event joins the same span chain the request's latency lives on;
- :func:`replay` folds an audit log back into the per-party spend table
  (charges add, refunds subtract-and-clamp, refusals spend nothing —
  the ledger's own arithmetic), so the trail alone reproduces the
  ledger state: ``python -m dpcorr obs budget`` is that check plus a
  per-party timeline view.

The trail is an *observer*, not the accounting source of truth: the
ledger's fsync-rename snapshot remains what restarts load, and a trail
write happens after the charge is durably persisted (losing a tail
event under crash can under-report the audit view but can never corrupt
the budget). Events are line-buffered appends; thread-safe.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Iterable, Mapping

EVENT_KINDS = ("charge", "refund", "refusal")


class AuditTrail:
    """Append-only JSONL budget-event log. ``path=None`` keeps the
    events in memory (``events()``) — what tests and the in-process
    stats view use; a path makes it durable."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.Lock()
        self._seq = 0  # guarded by: _lock
        self._mem: list[dict] = []  # guarded by: _lock
        self._fh = None  # guarded by: _lock
        self._observers: list = []  # guarded by: _lock
        if path is not None:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            # resume the sequence past an existing trail so a restarted
            # server appends monotonically instead of reusing seq 0
            if os.path.exists(path):
                self._seq = sum(1 for ln in open(path) if ln.strip())
            self._fh = open(path, "a", buffering=1)

    def record(self, kind: str, charges: Mapping[str, float],
               trace_id: str | None = None, **detail) -> dict:
        """Append one event; returns it (tests assert on the shape)."""
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown audit event kind {kind!r}; "
                             f"expected one of {EVENT_KINDS}")
        with self._lock:
            ev = {"seq": self._seq, "ts": time.time(), "kind": kind,
                  "charges": {str(p): float(e) for p, e in charges.items()},
                  "trace_id": trace_id}
            if detail:
                ev.update(detail)
            self._seq += 1
            if self._fh is not None:
                self._fh.write(json.dumps(ev) + "\n")
            else:
                self._mem.append(ev)
            observers = list(self._observers)
        # outside the trail lock: the flight recorder takes its own
        # ring lock and must not nest under ours
        for fn in observers:
            fn(ev)
        return ev

    def add_observer(self, fn) -> None:
        """Register ``fn(event_dict)`` to receive every recorded event
        (the flight recorder's audit ring)."""
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)

    def remove_observer(self, fn) -> None:
        with self._lock:
            if fn in self._observers:
                self._observers.remove(fn)

    def events(self) -> list[dict]:
        """The in-memory events (memory-backed trails only; for a
        durable trail read the file via :func:`read_events`)."""
        with self._lock:
            if self.path is not None:
                return read_events(self.path)
            return list(self._mem)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_events(path: str) -> list[dict]:
    """Load an audit JSONL file; ValueError names the first bad line."""
    events = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: bad audit line: {e}") from e
            if not isinstance(ev, dict) or ev.get("kind") not in EVENT_KINDS:
                raise ValueError(f"{path}:{i}: not an audit event")
            events.append(ev)
    return events


def _dedup_walk(events: Iterable[dict]):
    """Yield ``(kind, charges)`` applying the ledger's charge_id
    idempotency chronologically: the first charge carrying a given id
    spends it — even a ``dedup``-flagged one, which is how a re-charge
    event repairs a trail whose original charge line was lost to a
    crash between ledger persist and audit append — and every later
    charge with that id spends nothing. A refund forgets the id, so a
    *later* charge may legitimately reuse it. Events without a
    charge_id always apply (pre-idempotency trails and serve-path
    charges)."""
    applied: set = set()
    for ev in events:
        kind, cid = ev["kind"], ev.get("charge_id")
        if kind == "charge" and cid is not None:
            if cid in applied:
                yield ev, False
                continue
            applied.add(cid)
        elif kind == "refund" and cid is not None:
            applied.discard(cid)
        yield ev, True


def replay(events: Iterable[dict]) -> dict[str, float]:
    """Fold events into the per-party spend table using the ledger's
    own arithmetic (refunds clamp at zero; refusals spend nothing;
    charge_id-deduplicated charges spend once no matter how many times
    a resumed session re-ran them). The acceptance check:
    replay(trail) == ledger snapshot."""
    spent: dict[str, float] = {}
    for ev, applies in _dedup_walk(events):
        if not applies:
            continue
        if ev["kind"] == "charge":
            for p, e in ev["charges"].items():
                spent[p] = spent.get(p, 0.0) + float(e)
        elif ev["kind"] == "refund":
            for p, e in ev["charges"].items():
                spent[p] = max(0.0, spent.get(p, 0.0) - float(e))
    return spent


def replay_levels(events: Iterable[dict]) -> dict[str, dict]:
    """Replay split by budget level: ``{"party", "user", "global"}``
    spend tables (user keys are bare ids, ``user/`` prefix stripped).
    This is how ``obs budget --budget-dir`` folds a sharded per-user
    trail back to the budget directory's arithmetic — the ``user``
    table must equal each user's directory *lifetime* spend (renewals
    reset only the admission window and draw no audit event)."""
    from dpcorr.obs.budget_replay import fold_levels

    return fold_levels(replay(events))


def timeline(events: Iterable[dict], party: str | None = None) -> list[dict]:
    """Per-event cumulative view: each row is one event with the
    running post-event spend of every party it touched — the ε-spend
    timeline ``python -m dpcorr obs budget`` prints."""
    spent: dict[str, float] = {}
    rows = []
    for ev, applies in _dedup_walk(events):
        touched = {}
        for p, e in ev["charges"].items():
            if applies and ev["kind"] == "charge":
                spent[p] = spent.get(p, 0.0) + float(e)
            elif applies and ev["kind"] == "refund":
                spent[p] = max(0.0, spent.get(p, 0.0) - float(e))
            touched[p] = spent.get(p, 0.0)
        if party is not None and party not in ev["charges"]:
            continue
        rows.append({"seq": ev["seq"], "ts": ev["ts"], "kind": ev["kind"],
                     "trace_id": ev.get("trace_id"),
                     "charges": ev["charges"], "spent_after": touched})
    return rows
