"""Per-device memory watermarks and per-device transfer attribution.

The bench/roofline artifacts record *how fast* the device went; nothing
records *how full* it was — and the multi-device scaling work the
ROADMAP names will be memory-bound long before it is FLOP-bound (HBM
per chip is the scarce resource; see the accelerator guide's memory
hierarchy). This module samples what the backend exposes and publishes
it as ``dpcorr_device_*`` gauges, degrading gracefully by design:

- ``device.memory_stats()`` where the runtime implements it (TPU/GPU
  backends: ``bytes_in_use``, ``peak_bytes_in_use``, ``bytes_limit``);
  CPU backends typically return nothing — those fields are simply
  absent, never faked as zero.
- live-buffer sampling via ``jax.live_arrays()`` where available:
  buffer count and bytes per device — the "what is actually resident"
  view that catches a leaked donation or an accidental replication.
- the process-wide transfer counters (:mod:`dpcorr.obs.transfer`)
  split per device: today's pipelines place on one device, so the
  split attributes the totals to each dispatch's placement device
  (callers pass it; the default is the backend's device 0, which is
  exact for every single-device pipeline in the tree).

Everything is jax-gated at call time: importing this module costs
nothing and never pulls jax; on a jax-free box every probe returns
``{}`` and the gauges stay unpublished. ``bench.py`` stamps
:func:`watermarks_detail` into its artifact next to the transfer
deltas, and the serve/fleet plane scrapes the gauges like any other.
"""

from __future__ import annotations

import threading
from typing import Mapping

from dpcorr.obs.metrics import Registry, default_registry

#: memory_stats() keys we publish when the backend reports them,
#: mapped to gauge names (all bytes)
_MEM_KEYS = (
    ("bytes_in_use", "dpcorr_device_mem_bytes_in_use",
     "Device allocator bytes currently in use"),
    ("peak_bytes_in_use", "dpcorr_device_mem_peak_bytes",
     "Device allocator high-water mark (backend-reported)"),
    ("bytes_limit", "dpcorr_device_mem_limit_bytes",
     "Device allocator capacity"),
)


def _jax():
    try:
        import jax

        return jax
    except Exception:  # jax-free box: every probe degrades to empty
        return None


def device_label(device) -> str:
    """Stable per-device label: ``platform:id`` (matches how the
    compile cache and geometry autotuner key devices)."""
    return f"{getattr(device, 'platform', 'unknown')}:" \
           f"{getattr(device, 'id', 0)}"


def probe() -> dict[str, dict]:
    """One sample of every visible device: ``{device_label: stats}``.
    Fields appear only when the backend reports them; a jax-free
    process (or a backend with no memory introspection) yields ``{}``
    entries rather than invented zeros. Never raises."""
    jax = _jax()
    if jax is None:
        return {}
    out: dict[str, dict] = {}
    try:
        devices = list(jax.devices())
    except Exception:
        return {}
    for d in devices:
        stats: dict = {}
        ms = getattr(d, "memory_stats", None)
        if callable(ms):
            try:
                reported = ms() or {}
            except Exception:
                reported = {}
            for key, _, _ in _MEM_KEYS:
                if key in reported:
                    stats[key] = int(reported[key])
        out[device_label(d)] = stats
    # live buffers: version-gated (jax.live_arrays is the modern
    # spelling); arrays may be multi-device — attribute to each shard's
    # device so replication shows up as replication
    live = getattr(jax, "live_arrays", None)
    if callable(live):
        try:
            arrays = live()
        except Exception:
            arrays = []
        counts: dict[str, int] = {}
        nbytes: dict[str, int] = {}
        for a in arrays:
            for d in _array_devices(a):
                label = device_label(d)
                counts[label] = counts.get(label, 0) + 1
                nbytes[label] = nbytes.get(label, 0) + int(
                    getattr(a, "nbytes", 0))
        for label, rec in out.items():
            if label in counts:
                rec["live_buffers"] = counts[label]
                rec["live_buffer_bytes"] = nbytes[label]
    return out


def _array_devices(a) -> list:
    try:
        devs = a.devices()  # modern jax.Array
        return list(devs)
    except Exception:
        d = getattr(a, "device", None)
        if callable(d):
            try:
                return [d()]
            except Exception:
                return []
        return [d] if d is not None else []


class DeviceMonitor:
    """Samples device memory + splits transfer counters per device,
    publishing ``dpcorr_device_*`` gauges into ``registry`` and keeping
    its own high-water marks across samples (the backend peak resets
    with the allocator; the monitor's watermark survives for the bench
    artifact)."""

    def __init__(self, registry: Registry | None = None,
                 transfer_counters=None):
        self.registry = registry if registry is not None \
            else default_registry()
        r = self.registry
        self._mem_gauges = {
            key: r.gauge(gname, ghelp, labelnames=("device",))
            for key, gname, ghelp in _MEM_KEYS}
        self._live_count = r.gauge(
            "dpcorr_device_live_buffers",
            "Live jax buffers resident on the device",
            labelnames=("device",))
        self._live_bytes = r.gauge(
            "dpcorr_device_live_buffer_bytes",
            "Bytes held by live jax buffers on the device",
            labelnames=("device",))
        self._transfer = r.gauge(
            "dpcorr_device_transfer",
            "Process transfer counters (obs.transfer) attributed to "
            "the dispatch placement device",
            labelnames=("device", "counter"))
        self._counters = transfer_counters
        self._lock = threading.Lock()
        self._watermarks: dict[str, dict] = {}  # guarded by: _lock

    def sample(self, transfer_device: str | None = None) -> dict:
        """One sample: probe devices, publish gauges, fold watermarks.
        ``transfer_device`` names the device the process's transfer
        counters belong to; default is the first probed device (exact
        for single-device pipelines — multi-device callers say which)."""
        snap = probe()
        with self._lock:
            for label, stats in snap.items():
                for key, _, _ in _MEM_KEYS:
                    if key in stats:
                        self._mem_gauges[key].set(stats[key],
                                                  device=label)
                if "live_buffers" in stats:
                    self._live_count.set(stats["live_buffers"],
                                         device=label)
                    self._live_bytes.set(stats["live_buffer_bytes"],
                                         device=label)
                wm = self._watermarks.setdefault(label, {})
                for key in ("bytes_in_use", "peak_bytes_in_use",
                            "live_buffer_bytes", "live_buffers"):
                    if key in stats:
                        wm[key] = max(wm.get(key, 0), stats[key])
                if "bytes_limit" in stats:
                    wm["bytes_limit"] = stats["bytes_limit"]
        if self._counters is not None and snap:
            label = transfer_device if transfer_device is not None \
                else sorted(snap)[0]
            for counter, value in self._counters.snapshot().items():
                self._transfer.set(value, device=label, counter=counter)
        return snap

    def watermarks(self) -> dict[str, dict]:
        """Per-device high-water marks over this monitor's lifetime —
        what the bench artifact stamps."""
        with self._lock:
            return {label: dict(wm)
                    for label, wm in sorted(self._watermarks.items())}


def watermarks_detail(transfer_counters=None) -> dict[str, dict]:
    """One-shot probe for artifact stamping: a private registry (no
    cross-contamination with the process default), one sample, the
    watermark dict. Empty on a jax-free or introspection-free box —
    callers stamp it gated (``if devices: detail["devices"] = ...``)."""
    mon = DeviceMonitor(registry=Registry(),
                        transfer_counters=transfer_counters)
    mon.sample()
    return mon.watermarks()
