"""Fleet telemetry plane: scrape N instances, merge their telemetry exactly.

Every observability surface before this module sees exactly one process
— a registry renders its own counters, a tracer spools its own spans,
an audit trail replays its own ledger. The fleet the ROADMAP is heading
for ("aggregate qps scales with replicas, ledger/audit stay
binary-exact across the fleet") cannot even be *stated* without a layer
that folds many processes into one view. This module is that layer,
built pull-style (the collector scrapes; instances never push) and
jax-free (the operator story must not need an accelerator stack):

- **kind-aware exposition parsing** — :func:`parse_families` reads the
  text format :meth:`~dpcorr.obs.metrics.Registry.render` emits back
  into typed :class:`MetricFamily` objects (counter / gauge /
  histogram, with labels), strictly: a malformed line is a loud
  ``ValueError``, never a silently dropped series. The existing flat
  ``parse_exposition`` stays what it is — a value checker; merging
  needs kinds.
- **federated merge** — :func:`merge_families` unions per-instance
  families under an added ``instance`` label. Collisions are refused
  loudly: a duplicate instance name, a sample claiming a different
  instance identity than the target map, or two instances exposing one
  family under different kinds all raise instead of guessing.
- **exact aggregation** — :func:`aggregate_families` strips the
  ``instance`` label and folds: counters sum, cumulative histogram
  buckets (same ``le`` bounds by construction — every instance runs the
  same code) add bucket-wise, in sorted-instance order so the fold is
  deterministic and, for the integer counts that dominate, exact.
- **spool union** — :func:`fleet_chrome_trace` unions many span JSONL
  spools into ONE Chrome trace (one ``pid`` per instance, named via
  ``process_name`` metadata, so Perfetto shows the fleet side by side);
  :func:`fleet_replay` unions many audit spools into one fleet ε table
  that folds to the sum of per-instance ledgers —
  :func:`conservation` is the binary-exact gate the ``--fleet`` load
  arm and CI assert on.
- **the collector** — :class:`FleetCollector` scrapes N ``/metrics`` +
  ``/stats`` endpoints into a :class:`FleetSnapshot`; a dead instance
  becomes an ``error`` entry, never an exception (half a fleet view
  beats none during the incident that killed the other half).

See docs/OBSERVABILITY.md ("Fleet telemetry plane") for the operator
walkthrough and the worked 3-instance postmortem.
"""

from __future__ import annotations

import json
import math
import re
import urllib.error
import urllib.request
from typing import Iterable, Mapping

from dpcorr.obs.metrics import _fmt_value

#: the reserved label the merge layer owns; instances must not set it
INSTANCE_LABEL = "instance"

#: instrument kinds the merge layer knows how to fold
_KINDS = ("counter", "gauge", "histogram", "untyped")

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _unescape(v: str) -> str:
    return re.sub(r"\\(.)",
                  lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), v)


def _parse_value(raw: str) -> float:
    special = {"+Inf": math.inf, "-Inf": -math.inf, "NaN": math.nan}
    if raw in special:
        return special[raw]
    return float(raw)


class MetricFamily:
    """One exposition family: name, kind, help and its samples.

    ``samples`` is a list of ``(sample_name, labels, value)`` where
    ``labels`` is a tuple of ``(key, value)`` pairs sorted by key —
    a canonical form, so two families parsed from independently
    rendered expositions compare equal iff they carry the same data.
    For histograms the sample names are the exposition's own
    ``<name>_bucket`` / ``<name>_sum`` / ``<name>_count``.
    """

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name: str, kind: str, help: str = ""):
        if kind not in _KINDS:
            raise ValueError(f"{name}: unknown metric kind {kind!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.samples: list[tuple[str, tuple, float]] = []

    def add(self, sample_name: str, labels: Mapping[str, str] | Iterable,
            value: float) -> None:
        if isinstance(labels, Mapping):
            canon = tuple(sorted((str(k), str(v))
                                 for k, v in labels.items()))
        else:
            canon = tuple(sorted((str(k), str(v)) for k, v in labels))
        self.samples.append((sample_name, canon, float(value)))

    def __eq__(self, other) -> bool:
        if not isinstance(other, MetricFamily):
            return NotImplemented
        return (self.name == other.name and self.kind == other.kind
                and sorted(self.samples) == sorted(other.samples))

    def __repr__(self) -> str:
        return (f"MetricFamily({self.name!r}, {self.kind!r}, "
                f"samples={len(self.samples)})")

    def to_dict(self) -> dict:
        return {"name": self.name, "kind": self.kind, "help": self.help,
                "samples": [{"sample": s, "labels": dict(ls), "value": v}
                            for s, ls, v in sorted(self.samples)]}


def _family_for_sample(families: dict, sample_name: str):
    """Resolve which family a sample line belongs to: exact name, or —
    for ``_bucket``/``_sum``/``_count`` — its declared histogram."""
    fam = families.get(sample_name)
    if fam is not None:
        return fam
    for suffix in _HIST_SUFFIXES:
        if sample_name.endswith(suffix):
            base = families.get(sample_name[:-len(suffix)])
            if base is not None and base.kind == "histogram":
                return base
    return None


def parse_families(text: str) -> dict[str, MetricFamily]:
    """Parse exposition text (what :meth:`Registry.render` emits) into
    ``{family_name: MetricFamily}``, kind-aware and strict: a sample
    line that does not parse raises ``ValueError`` naming it — the
    fleet gates want a corrupted scrape to fail loudly, not fold a
    truncated counter into the aggregate."""
    families: dict[str, MetricFamily] = {}
    helps: dict[str, str] = {}
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                name, kind = parts[2], (parts[3] if len(parts) > 3
                                        else "untyped")
                families[name] = MetricFamily(name, kind,
                                              helps.get(name, ""))
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
                if parts[2] in families:
                    families[parts[2]].help = helps[parts[2]]
            continue  # other comments (e.g. # EXEMPLAR) pass through
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"exposition line {i}: unparseable sample "
                             f"{line!r}")
        sample_name = m.group("name")
        raw_labels = m.group("labels")
        labels: dict[str, str] = {}
        if raw_labels:
            stripped = re.sub(r"[,\s]", "", _LABEL_RE.sub("", raw_labels))
            if stripped:
                raise ValueError(f"exposition line {i}: bad label set "
                                 f"{{{raw_labels}}}")
            labels = {lm.group(1): _unescape(lm.group(2))
                      for lm in _LABEL_RE.finditer(raw_labels)}
        try:
            value = _parse_value(m.group("value"))
        except ValueError as e:
            raise ValueError(f"exposition line {i}: bad value "
                             f"{m.group('value')!r}") from e
        fam = _family_for_sample(families, sample_name)
        if fam is None:
            # sample with no TYPE declaration: carry it as untyped so a
            # hand-built exposition still merges (kind defaults safely)
            fam = families.setdefault(
                sample_name, MetricFamily(sample_name, "untyped",
                                          helps.get(sample_name, "")))
        fam.add(sample_name, labels, value)
    return families


def render_families(families: Mapping[str, MetricFamily]) -> str:
    """Re-expose families as exposition text — the same shape
    :meth:`Registry.render` emits, so a merged fleet registry is itself
    scrapeable, and ``parse_families(render_families(x)) == x`` (the
    round-trip the determinism tests pin)."""
    lines = []
    for name in sorted(families):
        fam = families[name]
        if fam.help:
            lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for sample_name, labels, value in sorted(fam.samples):
            if labels:
                inner = ",".join(
                    f'{k}="{_escape(v)}"' for k, v in labels)
                suffix = "{" + inner + "}"
            else:
                suffix = ""
            lines.append(f"{sample_name}{suffix} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def merge_families(per_instance: Mapping[str, Mapping[str, MetricFamily]],
                   ) -> dict[str, MetricFamily]:
    """Union per-instance families into one federated set, each sample
    gaining an ``instance`` label. Refused loudly: a sample claiming a
    *different* instance identity than the target map (an instance
    impersonating another) and a cross-instance kind clash both raise
    ``ValueError``; a sample whose self-reported ``instance`` matches
    (the serve layer's instance_info gauge) passes the cross-check."""
    merged: dict[str, MetricFamily] = {}
    for inst in sorted(per_instance):
        for name, fam in per_instance[inst].items():
            out = merged.get(name)
            if out is None:
                out = merged[name] = MetricFamily(name, fam.kind, fam.help)
            elif out.kind != fam.kind:
                raise ValueError(
                    f"instance {inst!r}: family {name!r} is a {fam.kind}, "
                    f"already merged as a {out.kind}")
            for sample_name, labels, value in fam.samples:
                claimed = dict(labels).get(INSTANCE_LABEL)
                if claimed is None:
                    out.add(sample_name,
                            labels + ((INSTANCE_LABEL, inst),), value)
                elif claimed == inst:
                    # self-reported identity (the serve layer's
                    # instance_info gauge) agreeing with the target map
                    # is the cross-check working; keep it as-is
                    out.add(sample_name, labels, value)
                else:
                    raise ValueError(
                        f"instance {inst!r}: sample {sample_name} claims "
                        f"{INSTANCE_LABEL}={claimed!r} — refusing to "
                        f"merge a colliding instance identity")
    return merged


def merge_expositions(expositions: Iterable[tuple[str, str]],
                      ) -> dict[str, MetricFamily]:
    """Merge ``(instance_name, exposition_text)`` pairs; duplicate
    instance names are refused loudly (two processes claiming one
    identity is an operator error, not a mergeable state)."""
    per_instance: dict[str, dict[str, MetricFamily]] = {}
    for inst, text in expositions:
        if inst in per_instance:
            raise ValueError(f"duplicate instance name {inst!r}")
        per_instance[inst] = parse_families(text)
    return merge_families(per_instance)


def aggregate_families(merged: Mapping[str, MetricFamily],
                       ) -> dict[str, MetricFamily]:
    """Fold a federated family set across instances: drop the
    ``instance`` label and sum samples that land on the same residual
    label set — counters and cumulative histogram buckets add exactly
    (every instance runs the same code, so bucket bounds agree by
    construction); gauges fold additively too, which is the right
    semantics for the level gauges the serve layer publishes (queue
    depth, cache size — fleet capacity is the sum of replica
    capacities). The fold iterates instances in sorted order, so the
    result is deterministic, byte for byte, across re-merges."""
    out: dict[str, MetricFamily] = {}
    for name in sorted(merged):
        fam = merged[name]
        agg = MetricFamily(name, fam.kind, fam.help)
        folded: dict[tuple[str, tuple], float] = {}
        order: list[tuple[str, tuple]] = []
        for sample_name, labels, value in sorted(
                fam.samples, key=lambda s: (s[0], s[1])):
            residual = tuple((k, v) for k, v in labels
                             if k != INSTANCE_LABEL)
            key = (sample_name, residual)
            if key not in folded:
                folded[key] = 0.0
                order.append(key)
            folded[key] += value
        for sample_name, residual in order:
            agg.samples.append((sample_name, residual,
                                folded[(sample_name, residual)]))
        out[name] = agg
    return out


def families_to_flat(families: Mapping[str, MetricFamily],
                     ) -> dict[str, float]:
    """``{"name{labels}": value}`` — the flat shape
    ``parse_exposition`` speaks, for gates that compare single series."""
    flat: dict[str, float] = {}
    for fam in families.values():
        for sample_name, labels, value in fam.samples:
            if labels:
                inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
                flat[f"{sample_name}{{{inner}}}"] = value
            else:
                flat[sample_name] = value
    return flat


# ------------------------------------------------------- span union ----
def _load_spans(spool) -> list[dict]:
    if isinstance(spool, str):
        from dpcorr.obs.trace import read_spans

        return read_spans(spool)
    return list(spool)


def fleet_chrome_trace(spools: Mapping[str, object]) -> dict:
    """Union many span spools (``{instance: jsonl_path_or_span_list}``)
    into ONE Chrome trace document: one ``pid`` per instance (sorted,
    so pids are stable), named via ``process_name`` metadata, one
    ``tid`` per originating thread within each instance — Perfetto then
    shows the whole fleet's request flow on one timeline, which is the
    entire point of a fleet postmortem."""
    events: list[dict] = []
    meta: list[dict] = []
    for pid, inst in enumerate(sorted(spools), start=1):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": inst}})
        tids: dict[str, int] = {}
        for sp in _load_spans(spools[inst]):
            tid = tids.setdefault(sp.get("thread", "main"), len(tids) + 1)
            events.append({
                "name": sp["name"], "ph": "X", "pid": pid, "tid": tid,
                "ts": sp.get("ts", 0.0) * 1e6,
                "dur": sp["dur_s"] * 1e6,
                "args": {**sp.get("attrs", {}),
                         "instance": inst,
                         "trace_id": sp.get("trace_id"),
                         "span_id": sp.get("span_id"),
                         "parent_id": sp.get("parent_id")},
            })
        meta.extend({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": t, "args": {"name": thread}}
                    for thread, t in tids.items())
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_fleet_chrome_trace(spools: Mapping[str, object],
                             out_path: str) -> str:
    with open(out_path, "w") as f:
        json.dump(fleet_chrome_trace(spools), f)
    return out_path


# ------------------------------------------------------ audit union ----
def _load_audit(spool) -> list[dict]:
    if isinstance(spool, str):
        from dpcorr.obs.audit import read_events

        return read_events(spool)
    return list(spool)


def fleet_replay(spools: Mapping[str, object]) -> dict:
    """Replay many audit spools (``{instance: jsonl_path_or_events}``)
    with the ledger's own arithmetic, per instance, then fold the
    per-party spends across instances in sorted-instance order. The
    fold is the definition of the fleet ε table: charge_id idempotency
    stays *per instance* (each instance owns its own ledger, so ids
    only ever dedup within one), and the fleet total for a party is
    exactly the sum of what each instance's ledger says it spent —
    which is what :func:`conservation` checks, binary-exact."""
    from dpcorr.obs.audit import replay

    per_instance = {inst: replay(_load_audit(spools[inst]))
                    for inst in sorted(spools)}
    fleet: dict[str, float] = {}
    for inst in sorted(per_instance):
        for party, eps in sorted(per_instance[inst].items()):
            fleet[party] = fleet.get(party, 0.0) + eps
    return {"per_instance": per_instance, "fleet": fleet}


def ledger_parties(stats_snapshot: Mapping) -> dict[str, float]:
    """Per-party spend out of one instance's ``/stats`` snapshot —
    the ledger side of the conservation equation."""
    parties = (stats_snapshot.get("ledger") or {}).get("parties", {})
    return {p: float(rec["spent"]) if isinstance(rec, Mapping)
            else float(rec)
            for p, rec in parties.items()}


def conservation(audit_spools: Mapping[str, object],
                 ledgers: Mapping[str, Mapping[str, float]]) -> dict:
    """The fleet ε-conservation gate: per instance, the audit replay
    must equal that instance's ledger spends *exactly* (``==`` on the
    floats — the ledger's dyadic charges make this well-defined), and
    the fleet fold of the replays must equal the fold of the ledgers,
    summed in the same sorted-instance order so both sides perform the
    identical float additions. Returns a verdict document the load arm
    and CI embed in their JSON artifacts."""
    replayed = fleet_replay(audit_spools)
    per_ok: dict[str, bool] = {}
    mismatches: list[dict] = []
    for inst in sorted(audit_spools):
        want = dict(ledgers.get(inst, {}))
        got = replayed["per_instance"].get(inst, {})
        ok = got == want
        per_ok[inst] = ok
        if not ok:
            mismatches.append({"instance": inst, "replay": got,
                               "ledger": want})
    ledger_fleet: dict[str, float] = {}
    for inst in sorted(ledgers):
        for party, eps in sorted(ledgers[inst].items()):
            ledger_fleet[party] = ledger_fleet.get(party, 0.0) + float(eps)
    fleet_ok = replayed["fleet"] == ledger_fleet
    return {"ok": all(per_ok.values()) and fleet_ok,
            "per_instance_ok": per_ok, "fleet_ok": fleet_ok,
            "fleet": replayed["fleet"], "ledger_fleet": ledger_fleet,
            "mismatches": mismatches}


# -------------------------------------------------------- collector ----
def parse_targets(spec) -> dict[str, str]:
    """Target specs: ``"name=url,name=url"`` (CLI), a ``{name: url}``
    mapping, or an iterable of ``name=url`` strings / ``(name, url)``
    pairs / bare urls (which get positional ``instance-N`` names).
    Duplicate names refuse loudly."""
    if isinstance(spec, str):
        items = [s for s in spec.split(",") if s.strip()]
    elif isinstance(spec, Mapping):
        items = list(spec.items())
    else:
        items = list(spec)
    out: dict[str, str] = {}
    for i, item in enumerate(items):
        if isinstance(item, (tuple, list)):
            name, url = item
        elif "=" in item and not item.startswith(("http://", "https://")):
            name, _, url = item.partition("=")
        else:
            name, url = f"instance-{i}", item
        name = name.strip()
        if name in out:
            raise ValueError(f"duplicate instance name {name!r} in "
                             f"fleet targets")
        out[name] = url.strip()
    if not out:
        raise ValueError("no fleet targets given")
    return out


class FleetSnapshot:
    """One scrape of the whole fleet. ``instances`` maps instance name
    to ``{"url", "error", "stats", "exposition"}`` — a dead instance
    carries its error string and ``None`` payloads, and every derived
    view (merge, aggregate) is computed over the live subset."""

    def __init__(self, instances: dict[str, dict]):
        self.instances = instances

    def live(self) -> dict[str, dict]:
        return {n: rec for n, rec in self.instances.items()
                if rec.get("error") is None}

    def errors(self) -> dict[str, str]:
        return {n: rec["error"] for n, rec in self.instances.items()
                if rec.get("error") is not None}

    def families(self) -> dict[str, dict[str, MetricFamily]]:
        return {n: parse_families(rec["exposition"])
                for n, rec in sorted(self.live().items())}

    def merged(self) -> dict[str, MetricFamily]:
        return merge_families(self.families())

    def aggregate(self) -> dict[str, MetricFamily]:
        return aggregate_families(self.merged())

    def exposition(self) -> str:
        """The federated registry re-exposed — itself scrapeable."""
        return render_families(self.merged())

    def stats(self) -> dict[str, dict]:
        return {n: rec["stats"] for n, rec in sorted(self.live().items())}

    def to_doc(self) -> dict:
        """The ``dpcorr obs fleet snapshot`` artifact: per-instance
        stats + errors, the merged exposition, and the aggregate as a
        flat series map (gates read single series out of it)."""
        return {
            "version": 1,
            "instances": {
                n: {"url": rec["url"], "error": rec.get("error"),
                    "stats": rec.get("stats")}
                for n, rec in sorted(self.instances.items())},
            "merged_exposition": self.exposition(),
            "aggregate": families_to_flat(self.aggregate()),
        }


class FleetCollector:
    """Pull-based collector over N serve instances. Construction
    validates the target map (duplicate names refuse loudly); each
    :meth:`scrape` is one poll of every instance's ``/metrics`` +
    ``/stats``."""

    def __init__(self, targets):
        self.targets = parse_targets(targets)

    def scrape(self, timeout_s: float = 5.0) -> FleetSnapshot:
        instances: dict[str, dict] = {}
        for name in sorted(self.targets):
            base = self.targets[name].rstrip("/")
            rec: dict = {"url": base, "error": None, "stats": None,
                         "exposition": None}
            try:
                with urllib.request.urlopen(f"{base}/stats",
                                            timeout=timeout_s) as resp:
                    rec["stats"] = json.loads(resp.read().decode("utf-8"))
                with urllib.request.urlopen(f"{base}/metrics",
                                            timeout=timeout_s) as resp:
                    rec["exposition"] = resp.read().decode("utf-8")
            except (urllib.error.URLError, ValueError, OSError) as e:
                rec["error"] = f"{type(e).__name__}: {e}"
                rec["stats"] = rec["exposition"] = None
            instances[name] = rec
        return FleetSnapshot(instances)
