"""Process-wide metrics registry with Prometheus text exposition.

The serving layer's ``ServeStats``, the kernel cache and the privacy
ledger previously each kept their own ad-hoc counters; this module is
the one spine they now share. Three instrument kinds, mirroring the
Prometheus data model the ``/metrics`` endpoint speaks:

- :class:`Counter` — monotone totals (admissions, flushes, compiles).
  Optionally labelled (``requests_refused_total{reason="budget"}``).
- :class:`Gauge`  — set-to-current values (queue depth, live kernels,
  per-party ε spend).
- :class:`Histogram` — bucketed observations with cumulative bucket
  counts plus ``_sum``/``_count`` (serving latency). Buckets are
  cumulative (each ``le`` bound counts everything at or below it),
  exactly the exposition scrapers expect.

A :class:`Registry` renders all of its instruments as Prometheus text
exposition (version 0.0.4 — the ``text/plain`` format every scraper
accepts). One process-wide default registry exists for the CLI server
(:func:`default_registry`); tests and embedded servers construct their
own so concurrent server instances never cross-contaminate counts.

Thread-safety: every mutation and read takes the instrument's lock —
the coalescer flush thread, many client threads and a scraper all touch
these concurrently (pinned by tests/test_obs.py's concurrency smoke).
"""

from __future__ import annotations

import math
import threading
from typing import Iterable, Mapping, Sequence

#: Default latency buckets (seconds) — tuned to the serving SLO range:
#: sub-ms in-process calls up through multi-second cold compiles.
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_NAME_OK = frozenset("abcdefghijklmnopqrstuvwxyz"
                     "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or not set(name) <= _NAME_OK:
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    """Prometheus sample formatting: integers render bare, +Inf/-Inf/NaN
    use the exposition spellings."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _labels_suffix(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    """Shared label plumbing: each child is keyed by its label-value
    tuple; unlabelled instruments use the single ``()`` child."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = _check_name(name)
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple, float] = {}  # guarded by: _lock

    def _key(self, labels: Mapping[str, str] | None) -> tuple:
        labels = labels or {}
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.labelnames)}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def samples(self) -> list[tuple[str, str, float]]:
        """(name, labels-suffix, value) triples for exposition."""
        with self._lock:
            return [(self.name, _labels_suffix(self.labelnames, k), v)
                    for k, v in sorted(self._children.items())]


class Counter(_Metric):
    """Monotone total. ``inc`` only goes up; negative deltas raise."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counter increment must be "
                             f">= 0, got {amount}")
        k = self._key(labels)
        with self._lock:
            self._children[k] = self._children.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        k = self._key(labels)
        with self._lock:
            return self._children.get(k, 0.0)


class Gauge(_Metric):
    """Set-to-current value; also supports inc/dec for level tracking."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._children[k] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._children[k] = self._children.get(k, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        k = self._key(labels)
        with self._lock:
            return self._children.get(k, 0.0)

    def remove(self, **labels) -> None:
        """Drop one labelled child (a party leaving the ledger)."""
        k = self._key(labels)
        with self._lock:
            self._children.pop(k, None)


class Histogram:
    """Bucketed observations, Prometheus-style: per-bucket *cumulative*
    counts keyed by upper bound ``le``, plus ``_sum`` and ``_count``.
    Unlabelled (the serving layer has exactly one latency stream per
    server; labelled histograms can be added when a consumer exists)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        self.name = _check_name(name)
        self.help = help
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs or any(b <= 0 for b in bs if not math.isinf(b)):
            raise ValueError(f"{name}: buckets must be positive, got {bs}")
        # the +Inf bucket is implicit: _count plays its role
        self.buckets = tuple(b for b in bs if not math.isinf(b))
        self._lock = threading.Lock()
        self._bucket_counts = [0] * len(self.buckets)  # guarded by: _lock
        self._sum = 0.0  # guarded by: _lock
        self._count = 0  # guarded by: _lock

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._bucket_counts[i] += 1

    def snapshot(self) -> dict:
        """{"buckets": {le: cumulative_count}, "sum": s, "count": n} —
        the JSON-friendly view ``/stats`` consumers can read without
        parsing exposition text."""
        with self._lock:
            return {
                "buckets": {repr(float(b)): c for b, c in
                            zip(self.buckets, self._bucket_counts)},
                "sum": self._sum,
                "count": self._count,
            }

    def samples(self) -> list[tuple[str, str, float]]:
        with self._lock:
            out = [(f"{self.name}_bucket", f'{{le="{_fmt_value(b)}"}}',
                    float(c))
                   for b, c in zip(self.buckets, self._bucket_counts)]
            out.append((f"{self.name}_bucket", '{le="+Inf"}',
                        float(self._count)))
            out.append((f"{self.name}_sum", "", self._sum))
            out.append((f"{self.name}_count", "", float(self._count)))
            return out


class Registry:
    """A named set of instruments with Prometheus text exposition.

    Re-registering a name returns the existing instrument when the kind
    matches (so modules can idempotently declare what they use) and
    raises on a kind clash — two subsystems silently sharing one name
    with different semantics is exactly the bug a registry exists to
    prevent.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}  # guarded by: _lock

    def _register(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}, requested {cls.__name__}")
                return m
            m = cls(name, help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames=labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames=labelnames)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, buckets=buckets)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> Iterable[object]:
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        """Prometheus text exposition (version 0.0.4) of every
        registered instrument — the ``GET /metrics`` body."""
        lines = []
        for m in sorted(self.metrics(), key=lambda m: m.name):
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, labels, value in m.samples():
                lines.append(f"{name}{labels} {_fmt_value(value)}")
        return "\n".join(lines) + "\n"


#: Exposition content type (what /metrics should send).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_default_registry: Registry | None = None
_default_lock = threading.Lock()


def default_registry() -> Registry:
    """The process-wide registry (the CLI server's). Lazily built so
    importing dpcorr.obs costs nothing."""
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = Registry()
        return _default_registry


def parse_exposition(text: str) -> dict[str, float]:
    """Parse exposition text back to ``{"name{labels}": value}`` — the
    scrape side of the single-source-of-truth check in
    ``benchmarks/serve_load.py`` and the CI smoke (not a general
    Prometheus parser; handles exactly what :meth:`Registry.render`
    emits)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        series, _, raw = line.rpartition(" ")
        v = {"+Inf": math.inf, "-Inf": -math.inf,
             "NaN": math.nan}.get(raw)
        out[series] = float(raw) if v is None else v
    return out
