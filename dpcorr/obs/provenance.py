"""ε-provenance: the federation's budget story as one checkable DAG.

The paper's premise is that the parties' data cannot meet — so after a
k-party matrix run, the only trustworthy account of where each unit of
privacy budget went is one *reconstructed from every party's
independent records* and checked for exact agreement. This module
builds that account (ISSUE 13): it merges per-party pair-link
transcripts, durable audit trails, and session journals into a DAG of

    column-release **artifacts** → **charge** events (party ledger,
    charge_id, plan share) → link **rounds** → finished **cells**

and structurally proves the two federation invariants the wire gate
(:func:`dpcorr.protocol.scan.scan_federation`) only passes/fails:
every artifact charged **exactly once** at its plan venue, and reused
**byte-identically** everywhere else — total spend at the
``2·f·ε·(k−1)`` optimum, float-for-float against
``FederationPlan.optimal_eps()``. Any divergence becomes a *named,
typed* entry attributing the offending party and artifact — hostile
inputs (a missing party view, a tampered charge amount, a re-noised
artifact, a truncated transcript) produce divergences, never crashes.

Fully jax-free: safe for the scan/lint tier and CI postmortems on
boxes with no accelerator stack. Exports JSON (``to_doc``) and
Graphviz DOT (``to_dot``); the ``dpcorr obs provenance`` CLI wraps
both and exits 1 on any divergence.
"""

from __future__ import annotations

import glob as globmod
import hashlib
import json
import math
import os
from dataclasses import dataclass, field

from dpcorr.obs.audit import read_events, replay
from dpcorr.protocol.matrix import FederationPlan
from dpcorr.protocol.messages import canonical_encode, read_transcript

#: Divergence kinds, append-only — consumers (CI gates, the console)
#: match on these strings.
DIVERGENCE_KINDS = (
    "missing-party-view",     # a plan party contributed no/partial records
    "truncated-transcript",   # a link transcript ends before its plan rounds
    "re-noised-artifact",     # one column released as >1 byte encodings
    "double-charged-artifact",  # one artifact charged in >1 rounds
    "tampered-charge",        # a charge amount disagrees with the plan share
    "eps-total-mismatch",     # reconstructed total != optimal_eps()
)


def _divergence(out: list, kind: str, party, detail: str,
                **attrs) -> None:
    assert kind in DIVERGENCE_KINDS, kind
    d = {"kind": kind, "party": party, "detail": detail}
    d.update({k: v for k, v in attrs.items() if v is not None})
    out.append(d)


@dataclass
class Provenance:
    """The explorable result: ``nodes`` maps node id → attrs (every
    node carries ``kind`` ∈ plan|artifact|charge|round|cell),
    ``edges`` is ``[src, dst, relation]`` triples, ``divergences`` the
    typed findings. ``ok`` iff no divergence survived."""

    fed: str
    nodes: dict = field(default_factory=dict)
    edges: list = field(default_factory=list)
    divergences: list = field(default_factory=list)
    total_eps: float = 0.0
    expected_eps: float = 0.0
    parties: dict = field(default_factory=dict)  # party -> spend summary

    @property
    def ok(self) -> bool:
        return not self.divergences

    # ------------------------------------------------------- exports ----
    def to_doc(self) -> dict:
        return {"provenance": 1, "fed": self.fed, "ok": self.ok,
                "eps": {"total": self.total_eps,
                        "optimal": self.expected_eps,
                        "parties": self.parties},
                "counts": {"nodes": len(self.nodes),
                           "edges": len(self.edges),
                           "divergences": len(self.divergences)},
                "nodes": {k: self.nodes[k] for k in sorted(self.nodes)},
                "edges": sorted(self.edges),
                "divergences": self.divergences}

    def to_dot(self) -> str:
        """Graphviz DOT: artifacts as boxes, charges as diamonds,
        rounds as ellipses, cells as plain nodes; divergent nodes red."""
        shapes = {"plan": "folder", "artifact": "box",
                  "charge": "diamond", "round": "ellipse",
                  "cell": "plaintext"}
        flagged = set()
        for d in self.divergences:
            for key in ("node", "artifact_node"):
                if d.get(key):
                    flagged.add(d[key])
        lines = [f'digraph "{self.fed}" {{', "  rankdir=LR;"]
        for nid in sorted(self.nodes):
            attrs = self.nodes[nid]
            label = attrs.get("label_text") or nid
            shape = shapes.get(attrs.get("kind"), "box")
            colour = ', color=red, fontcolor=red' \
                if nid in flagged else ""
            lines.append(f'  "{nid}" [shape={shape}, '
                         f'label="{label}"{colour}];')
        for src, dst, rel in sorted(self.edges):
            lines.append(f'  "{src}" -> "{dst}" [label="{rel}"];')
        lines.append("}")
        return "\n".join(lines) + "\n"

    # --------------------------------------------------------- query ----
    def cell_story(self, i: int, j: int) -> dict:
        """The postmortem query: everything that fed one cell — its
        round, the artifacts that round embedded, and the charges that
        paid for them (docs/OBSERVABILITY.md §Federation)."""
        cid = f"cell:{i},{j}"
        rounds = [src for src, dst, rel in self.edges
                  if dst == cid and rel == "finishes"]
        arts, charges = [], []
        for rid in rounds:
            arts.extend(src for src, dst, rel in self.edges
                        if dst == rid and rel == "released_in")
        for aid in arts:
            charges.extend(dst for src, dst, rel in self.edges
                           if src == aid and rel == "charged_by")
        charges.extend(src for src, dst, rel in self.edges
                       if dst == cid and rel == "covers")
        return {"cell": self.nodes.get(cid),
                "rounds": {r: self.nodes.get(r) for r in rounds},
                "artifacts": {a: self.nodes.get(a) for a in arts},
                "charges": {c: self.nodes.get(c)
                            for c in sorted(set(charges))},
                "divergences": [d for d in self.divergences
                                if d.get("cell") == [i, j]]}


# ====================================================== the builder ====

def _walk_party(party: str, sources, div: list) -> dict:
    """One party's evidence: releases (label → sha/bytes per session),
    charges seen on its gated sends, rounds, results. A transcript
    that cannot be read to the end is a *truncated-transcript*
    divergence, and whatever prefix was readable still counts as
    evidence — a hostile party must not be able to suppress its own
    records by corrupting their tail."""
    ev = {"releases": [], "sends": [], "rounds": {}, "results": [],
          "sessions": set()}
    for src in sources:
        try:
            entries = (read_transcript(src) if isinstance(src, str)
                       else list(src))
        except (OSError, ValueError) as e:
            _divergence(div, "truncated-transcript", party,
                        f"unreadable transcript: {e}",
                        path=src if isinstance(src, str) else None)
            continue
        for e in entries:
            w = e.get("wire", {})
            sess = w.get("session", "?")
            ev["sessions"].add(sess)
            payload = w.get("payload", {})
            mtype = w.get("msg_type")
            if mtype == "release" and isinstance(
                    payload.get("artifacts"), dict):
                r = payload.get("round")
                ev["rounds"].setdefault(
                    (sess, r), {"cells": payload.get("cells", []),
                                "ts": e.get("ts"), "result": False})
                for lab, group in payload["artifacts"].items():
                    enc = (canonical_encode(group)
                           if isinstance(group, dict)
                           else repr(group).encode())
                    ev["releases"].append({
                        "label": lab, "session": sess, "round": r,
                        "sha256": hashlib.sha256(enc).hexdigest(),
                        "bytes": len(enc)})
                if e.get("dir") == "send" and e.get("eps", 0) > 0:
                    ev["sends"].append({
                        "session": sess, "round": r, "side": "x",
                        "eps": float(e["eps"]),
                        "charge_id": e.get("charge_id"),
                        "labels": list(payload.get("charged", ())),
                        "trace_id": e.get("trace_id")})
            elif mtype == "result":
                r = payload.get("round")
                rd = ev["rounds"].setdefault(
                    (sess, r), {"cells": payload.get("cells", []),
                                "ts": e.get("ts"), "result": False})
                rd["result"] = True
                rd["cells"] = [list(c[:2])
                               for c in payload.get("cells", [])] \
                    or rd["cells"]
                ev["results"].append({"session": sess, "round": r,
                                      "cells": payload.get("cells",
                                                           [])})
                if e.get("dir") == "send" and e.get("eps", 0) > 0:
                    ev["sends"].append({
                        "session": sess, "round": r, "side": "y",
                        "eps": float(e["eps"]),
                        "charge_id": e.get("charge_id"),
                        "labels": list(payload.get("charged", ())),
                        "trace_id": e.get("trace_id")})
    return ev


def build_provenance(plan: FederationPlan, transcripts: dict,
                     audits: dict | None = None,
                     journals: dict | None = None) -> Provenance:
    """Merge every party's records into the provenance DAG.

    ``transcripts`` maps party name → list of its pair-link transcript
    paths (or pre-read entry lists); ``audits`` maps party name →
    audit-trail JSONL path (or event list) — optional, but exactly-once
    charging can only be *proved* against the durable trails;
    ``journals`` maps party name → list of its session-journal paths
    (adds resume lineage to the round nodes). Never raises on hostile
    input: every disagreement lands in ``divergences``."""
    audits = audits or {}
    journals = journals or {}
    div: list = []
    prov = Provenance(fed=plan.fed)
    nodes, edges = prov.nodes, prov.edges

    nodes["plan"] = {"kind": "plan", "fed": plan.fed,
                     "family": plan.family, "n": plan.n,
                     "eps": plan.eps, "k": plan.k,
                     "optimal_eps": plan.optimal_eps(),
                     "naive_eps": plan.naive_eps(),
                     "trace_id": plan.trace_id(),
                     "label_text": f"plan {plan.fed}"}

    # -- plan skeleton: artifacts, cells ------------------------------
    venues = plan.artifact_venues()
    label_owner = {lab: pname for pname, cols in plan.parties
                   for lab in cols}
    for (side, lab), venue in sorted(venues.items()):
        aid = f"artifact:{side}:{lab}"
        nodes[aid] = {"kind": "artifact", "side": side, "label": lab,
                      "owner": label_owner.get(lab),
                      "venue": list(venue),
                      "label_text": f"{side}:{lab}"}
        edges.append(["plan", aid, "schedules"])
    for i, j in plan.cells():
        cid = f"cell:{i},{j}"
        nodes[cid] = {"kind": "cell", "i": i, "j": j,
                      "venue": list(plan.cell_venue(i, j)),
                      "label_text": f"({i},{j})"}

    # -- party views --------------------------------------------------
    expected_sessions = {}
    for p, q in plan.links():
        sess = plan.link_session(p, q)
        expected_sessions.setdefault(p, set()).add(sess)
        expected_sessions.setdefault(q, set()).add(sess)
    evidence = {}
    for pname, _cols in plan.parties:
        sources = transcripts.get(pname)
        needs_wire = bool(expected_sessions.get(pname))
        if not sources:
            if needs_wire:
                _divergence(div, "missing-party-view", pname,
                            f"party {pname!r} shares "
                            f"{len(expected_sessions[pname])} link(s) "
                            "but contributed no transcripts — its view "
                            "of the federation cannot be cross-checked")
            evidence[pname] = _walk_party(pname, [], div)
            continue
        evidence[pname] = _walk_party(pname, sources, div)
        missing = expected_sessions.get(pname, set()) \
            - evidence[pname]["sessions"]
        for sess in sorted(missing):
            _divergence(div, "missing-party-view", pname,
                        f"party {pname!r} has no transcript for its "
                        f"link session {sess!r}", session=sess)

    # -- rounds + truncation + cells ----------------------------------
    for p, q in plan.links():
        sess = plan.link_session(p, q)
        plan_rounds = plan.link_rounds(p, q)
        seen: dict = {}
        for pname in (p, q):
            for (s, r), rd in evidence[pname]["rounds"].items():
                if s == sess and r is not None:
                    got = seen.setdefault(r, dict(rd))
                    got["result"] = got["result"] or rd["result"]
        for r, cells in enumerate(plan_rounds):
            rid = f"round:{sess}:{r}"
            rd = seen.get(r)
            nodes[rid] = {"kind": "round", "session": sess,
                          "link": f"{p}-{q}", "round": r,
                          "cells": [list(c) for c in cells],
                          "observed": rd is not None,
                          "finished": bool(rd and rd["result"]),
                          "ts": (rd or {}).get("ts"),
                          "label_text": f"{sess} r{r}"}
            for lab in plan.round_x_labels(p, q, r):
                edges.append([f"artifact:x:{lab}", rid, "released_in"])
            for _i, j in cells:
                edges.append([f"artifact:y:{plan.label(j)}", rid,
                              "released_in"])
            for i, j in cells:
                edges.append([rid, f"cell:{i},{j}", "finishes"])
        observed = {r for r in seen if r is not None}
        if any(evidence[pname]["sessions"] & {sess}
               for pname in (p, q)):
            want = set(range(len(plan_rounds)))
            gone = sorted(want - observed)
            half = sorted(r for r in observed & want
                          if not seen[r]["result"])
            if gone or half:
                culprit = [pname for pname in (p, q)
                           if sess in evidence[pname]["sessions"]]
                _divergence(
                    div, "truncated-transcript",
                    ",".join(culprit), f"link {sess!r} shows "
                    f"{len(observed)} of {len(plan_rounds)} plan "
                    f"rounds (missing {gone}, unfinished {half}) — "
                    "the transcript ends before the plan does",
                    session=sess, missing_rounds=gone,
                    unfinished_rounds=half)

    # -- journals: resume lineage on the round nodes ------------------
    for pname, paths in journals.items():
        for src in paths:
            try:
                with open(src, encoding="utf-8") as fh:
                    st = json.load(fh)
            except (OSError, ValueError):
                continue  # a journal is optional corroboration
            sess = st.get("session")
            for attrs in nodes.values():
                if attrs.get("kind") == "round" \
                        and attrs.get("session") == sess:
                    attrs.setdefault("journals", {})[pname] = {
                        "status": st.get("status"),
                        "trace_id": st.get("trace_id")}

    # -- byte-identity across every party's view ----------------------
    by_label: dict = {}
    for pname, ev in evidence.items():
        for rel in ev["releases"]:
            by_label.setdefault(rel["label"], {}).setdefault(
                rel["sha256"], set()).add((pname, rel["session"]))
    for lab, variants in sorted(by_label.items()):
        for side in ("x", "y"):
            aid = f"artifact:{side}:{lab}"
            if aid in nodes:
                one = sorted(variants)[0] if len(variants) == 1 \
                    else None
                nodes[aid]["sha256"] = one
                nodes[aid]["seen_by"] = sorted(
                    {p for ss in variants.values() for p, _ in ss})
        if len(variants) > 1:
            counts = sorted(variants.items(), key=lambda kv:
                            (len(kv[1]), sorted(kv[1])))
            minority_sha, minority = counts[0]
            suspects = sorted({p for p, _s in minority})
            owner = label_owner.get(lab)
            _divergence(
                div, "re-noised-artifact",
                ",".join(suspects) or owner,
                f"column {lab!r} (owner {owner!r}) appears as "
                f"{len(variants)} distinct byte encodings; minority "
                f"variant {minority_sha[:12]} seen only by "
                f"{suspects} — re-noised releases of one column are "
                "subtractable", artifact=lab,
                artifact_node=f"artifact:x:{lab}",
                variants={sha: sorted(f"{p}:{s}" for p, s in ss)
                          for sha, ss in variants.items()})

    # -- charges: wire + audit, exactly-once, plan amounts ------------
    # expected (labels, ε) per gated message, straight from the plan's
    # own arithmetic so the comparison is float-for-float exact
    expected_send: dict = {}
    for p, q in plan.links():
        sess = plan.link_session(p, q)
        for r in range(len(plan.link_rounds(p, q))):
            rc = plan.round_charges(p, q, r)
            expected_send[(sess, r, "x")] = (
                p, tuple(rc["release"]["labels"]),
                float(sum(rc["release"]["charges"].values())))
            expected_send[(sess, r, "y")] = (
                q, tuple(rc["result"]["labels"]),
                float(sum(rc["result"]["charges"].values())))
    audit_events = {}
    for pname, src in audits.items():
        try:
            audit_events[pname] = (read_events(src)
                                   if isinstance(src, str) else
                                   list(src))
        except (OSError, ValueError) as e:
            _divergence(div, "missing-party-view", pname,
                        f"audit trail unreadable: {e}")
    charge_total: dict = {}
    charged_venues: dict = {}
    for pname, ev in evidence.items():
        by_id = {}
        for a in audit_events.get(pname, []):
            cid = (a.get("detail") or {}).get("charge_id") \
                if isinstance(a.get("detail"), dict) \
                else a.get("charge_id")
            if a.get("kind") == "charge" and cid:
                by_id[cid] = a
        for send in ev["sends"]:
            if not send["labels"]:
                continue  # reuse round: empty charge map, nothing due
            cid = send["charge_id"] or \
                f"{send['session']}:r{send['round']}:{send['side']}"
            nid = f"charge:{cid}"
            _payer, want_labels, expected = expected_send.get(
                (send["session"], send["round"], send["side"]),
                (pname, (), 0.0))
            nodes[nid] = {"kind": "charge", "party": pname,
                          "charge_id": cid, "eps": send["eps"],
                          "expected_eps": expected,
                          "session": send["session"],
                          "round": send["round"],
                          "trace_id": send["trace_id"],
                          "source": "transcript",
                          "label_text":
                              f"{pname} ε={send['eps']:g}"}
            for lab in send["labels"]:
                aid = f"artifact:{send['side']}:{lab}"
                edges.append([aid, nid, "charged_by"])
                charged_venues.setdefault(
                    (send["side"], lab), []).append(
                    (pname, send["session"], send["round"]))
            rid = f"round:{send['session']}:{send['round']}"
            if rid in nodes:
                edges.append([nid, rid, "funds"])
            if send["eps"] != expected \
                    or tuple(send["labels"]) != want_labels:
                _divergence(
                    div, "tampered-charge", pname,
                    f"gated send {cid!r} charged ε={send['eps']!r} "
                    f"for labels {send['labels']} but the plan "
                    f"assigns ε={expected!r} for "
                    f"labels {list(want_labels)}",
                    charge_id=cid, node=nid,
                    labels=send["labels"])
            audit_ev = by_id.get(cid)
            if audit_ev is not None:
                trail_eps = float(sum(
                    (audit_ev.get("charges") or {}).values()))
                nodes[nid]["audit_eps"] = trail_eps
                nodes[nid]["source"] = "transcript+audit"
                if trail_eps != send["eps"]:
                    _divergence(
                        div, "tampered-charge", pname,
                        f"charge {cid!r}: transcript says "
                        f"ε={send['eps']!r}, the durable audit trail "
                        f"says ε={trail_eps!r} — the records disagree",
                        charge_id=cid, node=nid,
                        labels=send["labels"])
            charge_total.setdefault(pname, []).append(
                (cid, send["eps"]))
        # local cells: the plan-derived local charge (audit-backed when
        # a trail is present)
        lc = plan.local_charges(pname)
        if lc["charges"]:
            cid = lc["charge_id"]
            nid = f"charge:{cid}"
            expected = float(sum(lc["charges"].values()))
            got = expected
            source = "plan"
            audit_ev = by_id.get(cid)
            if audit_ev is not None:
                got = float(sum(
                    (audit_ev.get("charges") or {}).values()))
                source = "audit"
            elif pname in audit_events:
                _divergence(
                    div, "tampered-charge", pname,
                    f"local charge {cid!r} (ε={expected:g}) is absent "
                    f"from {pname!r}'s audit trail — local cells were "
                    "computed without the recorded spend",
                    charge_id=cid, node=nid)
            nodes[nid] = {"kind": "charge", "party": pname,
                          "charge_id": cid, "eps": got,
                          "expected_eps": expected, "source": source,
                          "label_text": f"{pname} local ε={got:g}"}
            if got != expected:
                _divergence(
                    div, "tampered-charge", pname,
                    f"local charge {cid!r}: audit trail says "
                    f"ε={got!r}, the plan assigns ε={expected!r}",
                    charge_id=cid, node=nid)
            for side, lab in lc["artifacts"]:
                edges.append([f"artifact:{side}:{lab}", nid,
                              "charged_by"])
            for i, j in plan.local_cells(pname):
                edges.append([nid, f"cell:{i},{j}", "covers"])
            charge_total.setdefault(pname, []).append((cid, got))

    for (side, lab), sites in sorted(charged_venues.items()):
        uniq = sorted({(s, r) for _p, s, r in sites})
        if len(uniq) > 1:
            _divergence(
                div, "double-charged-artifact",
                ",".join(sorted({p for p, _s, _r in sites})),
                f"({side}, {lab!r}) charged in {len(uniq)} rounds "
                f"{uniq} — the plan charges each artifact exactly "
                "once", artifact=lab,
                artifact_node=f"artifact:{side}:{lab}")

    # -- totals: float-for-float at the optimum -----------------------
    per_party = {}
    for pname, pairs in sorted(charge_total.items()):
        per_party[pname] = math.fsum(e for _cid, e in sorted(pairs))
    # audit replay is the stronger per-party source when present: it
    # folds refunds and duplicate charge_ids the transcript can't see
    for pname, events in audit_events.items():
        spent = replay(events).get(pname)
        if spent is not None:
            per_party[pname] = spent
    prov.parties = {
        p: {"spent": per_party.get(p, 0.0),
            "share": plan.party_eps().get(p, 0.0)}
        for p, _c in plan.parties}
    prov.total_eps = math.fsum(per_party.get(p, 0.0)
                               for p, _c in plan.parties)
    # the expected total is the plan's *own* charge arithmetic folded
    # the same way as the observed spend (fsum of per-party shares in
    # party order) — optimal_eps()'s single multiply can differ in the
    # last ulp for arbitrary ε, and that is not a divergence
    prov.expected_eps = math.fsum(plan.party_eps().get(p, 0.0)
                                  for p, _c in plan.parties)
    if prov.total_eps != prov.expected_eps:
        worst = sorted(
            ((p, v["spent"] - v["share"])
             for p, v in prov.parties.items()),
            key=lambda kv: -abs(kv[1]))
        _divergence(
            div, "eps-total-mismatch",
            worst[0][0] if worst and worst[0][1] else None,
            f"reconstructed federation spend {prov.total_eps!r} != "
            f"optimal_eps() {prov.expected_eps!r} "
            f"(per-party deltas: "
            f"{ {p: round(d, 12) for p, d in worst if d} })")
    prov.divergences = div
    return prov


# ===================================================== CLI plumbing ====

def discover_federation(plan_path: str,
                        transcript_dir: str | None = None,
                        transcript_specs=None,
                        audit_specs=None,
                        journal_dir: str | None = None):
    """Resolve the CLI's file arguments into :func:`build_provenance`
    inputs. Transcripts are grouped by the party name embedded in the
    ``{session}.{party}.jsonl`` convention every federation driver
    writes; explicit ``NAME=PATH`` specs override."""
    with open(plan_path, encoding="utf-8") as fh:
        doc = json.load(fh)
    plan = FederationPlan.from_public(doc.get("plan", doc))
    transcripts: dict = {}
    paths = []
    if transcript_dir:
        for path in sorted(globmod.glob(
                os.path.join(transcript_dir, "*.jsonl"))):
            base = os.path.basename(path)
            if not base.startswith(("audit.", "trace.")):
                paths.append(path)
    for spec in transcript_specs or []:
        name, sep, path = spec.partition("=")
        if sep:
            transcripts.setdefault(name, []).append(path)
        else:
            paths.append(spec)
    known = {p for p, _c in plan.parties}
    for path in paths:
        parts = os.path.basename(path).split(".")
        pname = parts[-2] if len(parts) >= 3 else None
        if pname in known:
            transcripts.setdefault(pname, []).append(path)
    audits: dict = {}
    for spec in audit_specs or []:
        pname, sep, path = spec.partition("=")
        if not sep:
            raise ValueError(f"--audit {spec!r}: expected NAME=PATH")
        audits[pname] = path
    journals: dict = {}
    if journal_dir:
        for path in sorted(globmod.glob(
                os.path.join(journal_dir, "journal.*.json"))):
            parts = os.path.basename(path).split(".")
            if len(parts) >= 3 and parts[1] in known:
                journals.setdefault(parts[1], []).append(path)
    return plan, transcripts, audits, journals
