"""Live ops console: ``dpcorr obs top`` — a terminal view of a server.

Scrapes the serving front end's own endpoints (``GET /stats`` for the
structured snapshot, ``GET /metrics`` for the exposition series — the
same two sources every dashboard would use, so what the console shows
is exactly what production monitoring sees) and renders a compact
refreshing frame:

- queue depth / max-queue pressure and the flush EWMA;
- circuit-breaker state per tripped bucket and the brownout latch;
- SLO burn rate (the rolling-window gauges serve.stats publishes:
  fraction of recent requests over the latency SLO);
- compile activity (kernel compiles / hits / dedup, cache size);
- latency p50/p99 with the exemplar trace IDs linking slow buckets to
  concrete requests;
- top-ε principals — the parties spending budget fastest, from the
  ledger snapshot.

``--once`` prints a single frame and exits (the CI smoke); otherwise
the frame redraws every ``--interval`` seconds until interrupted.

stdlib-only and jax-free on purpose: this runs on an operator laptop
against a remote server, under the CLI's ``jax_free`` dispatch.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

from dpcorr.obs.metrics import parse_exposition

#: ANSI clear-screen + home — what the refresh loop prefixes frames with.
_CLEAR = "\x1b[2J\x1b[H"


def scrape(base_url: str, timeout_s: float = 5.0) -> dict:
    """One poll: ``{"stats": <//stats JSON>, "metrics": {series: value}}``.
    Raises ``urllib.error.URLError`` / ``ValueError`` on an unreachable
    or non-conforming server — the caller decides whether to retry."""
    base = base_url.rstrip("/")
    with urllib.request.urlopen(f"{base}/stats",
                                timeout=timeout_s) as resp:
        stats = json.loads(resp.read().decode("utf-8"))
    with urllib.request.urlopen(f"{base}/metrics",
                                timeout=timeout_s) as resp:
        metrics = parse_exposition(resp.read().decode("utf-8"))
    return {"stats": stats, "metrics": metrics}


def _fmt_eps(v: float) -> str:
    return f"{v:.4g}"


def top_parties(ledger_snapshot: dict | None, k: int = 5) -> list[tuple]:
    """(party, spent, budget) rows, highest spend first."""
    if not ledger_snapshot:
        return []
    parties = ledger_snapshot.get("parties", {})
    rows = []
    for name, rec in parties.items():
        if isinstance(rec, dict):
            rows.append((name, float(rec.get("spent", 0.0)),
                         float(rec.get("budget", 0.0))))
        else:
            rows.append((name, float(rec), 0.0))
    rows.sort(key=lambda r: r[1], reverse=True)
    return rows[:k]


def render_frame(stats: dict, metrics: dict,
                 now: float | None = None) -> str:
    """One console frame from a scrape — pure (canned-dict testable)."""
    lines = []
    ts = time.strftime("%H:%M:%S",
                       time.localtime(now if now is not None
                                      else time.time()))
    lines.append(f"dpcorr obs top  ·  {ts}")
    lines.append("-" * 64)

    depth = stats.get("queue_depth", 0)
    ewma = stats.get("flush_ewma_s", 0.0)
    lines.append(f"queue depth : {depth:>6}    flush ewma: {ewma * 1e3:8.2f} ms")

    brk = stats.get("breaker", {})
    tripped = brk.get("tripped_buckets", {})
    state = ("OK" if not tripped else
             f"{brk.get('open', 0)} open / {brk.get('half_open', 0)} half-open")
    lines.append(f"breaker     : {state}")
    for bucket, st in sorted(tripped.items()):
        lines.append(f"              {bucket}: {st}")
    lines.append(f"brownout    : "
                 f"{'ACTIVE' if stats.get('brownout_active') else 'off'}")

    burn = stats.get("slo", {})
    if burn:
        lines.append(
            f"slo burn    : {burn.get('burn_rate', 0.0) * 100:6.2f}% of "
            f"{burn.get('window_requests', 0)} req over "
            f"{burn.get('slo_s', 0.0) * 1e3:g} ms "
            f"(window {burn.get('window_s', 0.0):g}s)")

    lines.append(
        f"kernels     : {stats.get('kernel_compiles', 0)} compiles / "
        f"{stats.get('kernel_hits', 0)} hits / "
        f"{stats.get('kernel_compile_dedup', 0)} dedup   "
        f"cache {stats.get('kernel_cache_size', 0)}")

    rec = stats.get("recompiles", {})
    if rec and any(rec.values()):
        lines.append(
            f"recompiles  : {rec.get('new-signature', 0)} new-signature / "
            f"{rec.get('cache-evict', 0)} cache-evict / "
            f"{rec.get('jit-fallback', 0)} jit-fallback")

    lat = stats.get("latency_s", {})
    if lat:
        lines.append(f"latency     : p50 {lat.get('p50', 0.0) * 1e3:8.2f} ms"
                     f"   p99 {lat.get('p99', 0.0) * 1e3:8.2f} ms")
    ex = stats.get("exemplars", {})
    if ex:
        slowest = max(ex.items(),
                      key=lambda kv: kv[1].get("value", 0.0))
        lines.append(f"exemplar    : le={slowest[0]} "
                     f"trace={slowest[1].get('trace_id')} "
                     f"({slowest[1].get('value', 0.0) * 1e3:.2f} ms)")

    costs = stats.get("costs", {})
    if costs:
        lines.append(
            f"cost window : {costs.get('records', 0)} records   "
            f"kernel {costs.get('kernel_s', 0.0):.3f}s   "
            f"queue {costs.get('queue_wait_s', 0.0):.3f}s   "
            f"compile {costs.get('compile_wait_s', 0.0):.3f}s")

    lines.append(
        f"traffic     : {stats.get('requests_total', 0)} admitted   "
        f"{sum(stats.get('refused', {}).values())} refused   "
        f"{sum(stats.get('shed', {}).values())} shed   "
        f"{stats.get('requests_failed', 0)} failed")

    rows = top_parties(stats.get("ledger"))
    if rows:
        lines.append("top ε       : " + "   ".join(
            f"{name}={_fmt_eps(spent)}"
            + (f"/{_fmt_eps(budget)}" if budget else "")
            for name, spent, budget in rows))

    bd = stats.get("budget_dir")
    if bd:
        c = bd.get("counters", {})
        lines.append(
            f"budget dir  : {bd.get('shards', 0)} shards   "
            f"{bd.get('resident_users', 0)} resident / "
            f"{bd.get('evicted_users', 0)} evicted users   "
            f"{c.get('rehydrations', 0)} rehydrations")
        refusals = bd.get("refusals_by_level", {})
        if any(refusals.values()):
            lines.append("  refusals  : " + "   ".join(
                f"{lvl}={refusals.get(lvl, 0)}"
                for lvl in ("user", "party", "global")))
    return "\n".join(lines)


def render_fleet_frame(snapshot, now: float | None = None) -> str:
    """One fleet frame from a :class:`dpcorr.obs.fleet.FleetSnapshot` —
    one row per instance (dead instances marked DOWN with their scrape
    error) plus an aggregate line computed from the merged registry, so
    the totals the console shows are exactly what the federated
    exposition would report."""
    lines = []
    ts = time.strftime("%H:%M:%S",
                       time.localtime(now if now is not None
                                      else time.time()))
    n_live = len(snapshot.live())
    n_all = len(snapshot.instances)
    lines.append(f"dpcorr obs top --fleet  ·  {ts}  ·  "
                 f"{n_live}/{n_all} instances up")
    lines.append("-" * 72)
    lines.append(f"{'instance':<14} {'done':>7} {'refused':>7} "
                 f"{'queue':>5} {'shards':>7} {'p50 ms':>8} "
                 f"{'p99 ms':>8}  top ε")
    lease_owned: dict[str, int] = {}  # instance -> shards held
    lease_total = 0  # n_shards of the shared directory (0 = no fleet)
    for name in sorted(snapshot.instances):
        rec = snapshot.instances[name]
        if rec.get("error") is not None:
            lines.append(f"{name:<14} DOWN  {rec['error']}")
            continue
        stats = rec.get("stats") or {}
        lat = stats.get("latency_s", {})
        rows = top_parties(stats.get("ledger"), k=1)
        top = (f"{rows[0][0]}={_fmt_eps(rows[0][1])}" if rows else "-")
        done = (stats.get("batched_requests", 0)
                + stats.get("unbatched_requests", 0))
        leases = stats.get("leases")
        if leases:
            held = len(leases.get("owned", ()))
            lease_owned[name] = held
            lease_total = max(lease_total,
                              int(leases.get("n_shards") or 0))
            shards = f"{held}/{leases.get('n_shards', '?')}"
        else:
            shards = "-"
        lines.append(
            f"{name:<14} {done:>7} "
            f"{sum(stats.get('refused', {}).values()):>7} "
            f"{stats.get('queue_depth', 0):>5} "
            f"{shards:>7} "
            f"{lat.get('p50', 0.0) * 1e3:>8.2f} "
            f"{lat.get('p99', 0.0) * 1e3:>8.2f}  {top}")
    lines.append("-" * 72)
    if lease_owned:
        held = sum(lease_owned.values())
        own = "  ".join(f"{n}={k}" for n, k in sorted(lease_owned.items()))
        orphans = max(0, lease_total - held)
        lines.append(f"leases      : {held}/{lease_total} shards held "
                     f"({orphans} orphaned)   {own}")
    if n_live:
        agg = snapshot.aggregate()

        def total(name: str) -> float:
            # sum every child of the family (completed_total is
            # labelled by mode; refused_total by reason)
            fam = agg.get(name)
            if fam is None:
                return 0.0
            return sum(v for s, _, v in fam.samples if s == name)

        lines.append(
            "fleet       : "
            f"{total('dpcorr_serve_requests_completed_total'):g} done   "
            f"{total('dpcorr_serve_requests_refused_total'):g} refused   "
            f"{total('dpcorr_serve_requests_failed_total'):g} failed   "
            f"queue {total('dpcorr_serve_queue_depth'):g}")
    else:
        lines.append("fleet       : no live instances")
    return "\n".join(lines)


def render_federation_frame(snapshot, now: float | None = None) -> str:
    """One federation frame from a :class:`~dpcorr.obs.fleet.FleetSnapshot`
    of party processes (``dpcorr federation party --obs-port``): one
    row per party — matrix cells completed, link count, ε spent against
    the plan share, round count and mean round latency, release-cache
    hits/builds — plus a federation line proving all live parties agree
    on the fed id and the single plan-derived trace id."""
    lines = []
    ts = time.strftime("%H:%M:%S",
                       time.localtime(now if now is not None
                                      else time.time()))
    n_live = len(snapshot.live())
    n_all = len(snapshot.instances)
    lines.append(f"dpcorr obs top --federation  ·  {ts}  ·  "
                 f"{n_live}/{n_all} parties up")
    lines.append("-" * 76)
    lines.append(f"{'party':<12} {'cells':>9} {'links':>5} "
                 f"{'ε spent/share':>15} {'rounds':>6} "
                 f"{'rt mean ms':>10} {'cache h/b':>9}")
    families = snapshot.families()
    feds, traces, done_total, cells_total = set(), set(), 0, 0
    for name in sorted(snapshot.instances):
        rec = snapshot.instances[name]
        if rec.get("error") is not None:
            lines.append(f"{name:<12} DOWN  {rec['error']}")
            continue
        stats = rec.get("stats") or {}
        fams = families.get(name, {})

        def total(family: str, sample: str | None = None,
                  **match) -> float:
            fam = fams.get(family)  # noqa: B023 (read-only loop var)
            if fam is None:
                return 0.0
            want = sample if sample is not None else family
            return sum(v for s, ls, v in fam.samples
                       if s == want
                       and all(dict(ls).get(k) == mv
                               for k, mv in match.items()))

        feds.add(stats.get("fed"))
        traces.add(stats.get("trace_id"))
        done = int(stats.get("cells_done", 0))
        out_of = int(stats.get("cells_total", 0))
        done_total, cells_total = done_total + done, max(cells_total,
                                                         out_of)
        eps = stats.get("eps", {})
        rounds = total("dpcorr_federation_rounds_total")
        rt_count = total("dpcorr_federation_round_latency_seconds",
                         "dpcorr_federation_round_latency_seconds_count")
        rt_sum = total("dpcorr_federation_round_latency_seconds",
                       "dpcorr_federation_round_latency_seconds_sum")
        rt_mean = (rt_sum / rt_count * 1e3) if rt_count else 0.0
        hits = total("dpcorr_federation_release_cache_total",
                     outcome="hit")
        builds = total("dpcorr_federation_release_cache_total",
                       outcome="build")
        lines.append(
            f"{name:<12} {done:>4}/{out_of:<4} "
            f"{len(stats.get('links', ())):>5} "
            f"{_fmt_eps(eps.get('spent', 0.0)):>7}/"
            f"{_fmt_eps(eps.get('share', 0.0)):<7} "
            f"{rounds:>6g} {rt_mean:>10.2f} "
            f"{hits:>4g}/{builds:<4g}")
    lines.append("-" * 76)
    if n_live:
        fed = feds.pop() if len(feds) == 1 else f"DISAGREE {sorted(feds)}"
        trace = (traces.pop() if len(traces) == 1
                 else f"DISAGREE {sorted(traces)}")
        lines.append(f"federation  : {fed}   trace {trace}   "
                     f"cells {done_total} done "
                     f"(matrix {cells_total})")
    else:
        lines.append("federation  : no live parties")
    return "\n".join(lines)


def run_federation_top(targets, interval_s: float = 2.0,
                       once: bool = False, out=None,
                       max_frames: int | None = None) -> int:
    """The ``dpcorr obs top --federation`` loop over party
    ``--obs-port`` endpoints; exit contract mirrors
    :func:`run_fleet_top`."""
    from dpcorr.obs.fleet import FleetCollector
    emit = out if out is not None else print
    collector = FleetCollector(targets)
    frames = 0
    while True:
        snapshot = collector.scrape()
        if not snapshot.live() and frames == 0:
            emit("obs top --federation: no live parties:")
            for name, err in sorted(snapshot.errors().items()):
                emit(f"  {name}: {err}")
            return 1
        frame = render_federation_frame(snapshot)
        if once:
            emit(frame)
            return 0
        emit(_CLEAR + frame)
        frames += 1
        if max_frames is not None and frames >= max_frames:
            return 0
        time.sleep(interval_s)


def run_fleet_top(targets, interval_s: float = 2.0, once: bool = False,
                  out=None, max_frames: int | None = None) -> int:
    """The ``dpcorr obs top --fleet`` loop. Exit 0 after any frame with
    at least one live instance; 1 when the first scrape reaches nobody
    (mirrors :func:`run_top`'s unreachable-server contract)."""
    from dpcorr.obs.fleet import FleetCollector
    emit = out if out is not None else print
    collector = FleetCollector(targets)
    frames = 0
    while True:
        snapshot = collector.scrape()
        if not snapshot.live() and frames == 0:
            emit("obs top --fleet: no live instances:")
            for name, err in sorted(snapshot.errors().items()):
                emit(f"  {name}: {err}")
            return 1
        frame = render_fleet_frame(snapshot)
        if once:
            emit(frame)
            return 0
        emit(_CLEAR + frame)
        frames += 1
        if max_frames is not None and frames >= max_frames:
            return 0
        time.sleep(interval_s)


def render_stream_frame(stats: dict, metrics: dict,
                        now: float | None = None) -> str:
    """One ``obs top --stream`` frame over a ``dpcorr stream``
    instance's /stats + /metrics — pure (canned-dict testable)."""
    lines = []
    ts = time.strftime("%H:%M:%S",
                       time.localtime(now if now is not None
                                      else time.time()))
    lines.append(f"dpcorr obs top --stream  ·  {ts}")
    lines.append("-" * 64)

    win = stats.get("window", {})
    shape = f"{win.get('size_s', 0):g}s"
    if win.get("slide_s"):
        shape += f" / slide {win['slide_s']:g}s"
    shape += f"   late bound {win.get('late_s', 0):g}s"
    lines.append(f"stream      : {stats.get('stream_id', '?')}   "
                 f"families {','.join(stats.get('families', []))}")
    lines.append(f"window      : {shape}")

    wm = stats.get("watermark")
    lag = stats.get("watermark_lag_s")
    if lag is None:
        lag = metrics.get("dpcorr_stream_watermark_lag_seconds")
    lines.append(
        f"watermark   : {'—' if wm is None else f'{wm:.3f}'}   "
        f"lag {'—' if lag is None else f'{lag:.1f}s'}   "
        f"open {stats.get('open_windows', 0)} windows / "
        f"{stats.get('pending_rows', 0)} pending rows")

    eps_w = stats.get("eps_per_window", {})
    released = stats.get("released", 0)
    lines.append(
        f"windows     : {released} released   "
        f"{len(stats.get('refused', []))} refused   "
        f"ε/window " + "  ".join(f"{p}={_fmt_eps(v)}"
                                 for p, v in sorted(eps_w.items())))

    overload_key = 'dpcorr_stream_batches_total{kind="overload"}'
    lines.append(
        f"ingest      : {stats.get('seen_batches', 0)} batches   "
        f"{int(metrics.get('dpcorr_stream_rows_total', 0))} rows   "
        f"{stats.get('late_refused', 0)} late refused   "
        f"{int(metrics.get(overload_key, 0))} overload")

    rel_count = metrics.get(
        'dpcorr_stream_release_seconds_count', 0)
    rel_sum = metrics.get('dpcorr_stream_release_seconds_sum', 0.0)
    if rel_count:
        lines.append(f"release     : {rel_sum / rel_count * 1e3:8.2f} ms"
                     f" mean over {int(rel_count)} windows")

    rows = top_parties(stats.get("ledger"))
    if rows:
        lines.append("top ε       : " + "   ".join(
            f"{name}={_fmt_eps(spent)}"
            + (f"/{_fmt_eps(budget)}" if budget else "")
            for name, spent, budget in rows))

    bd = stats.get("budget_dir")
    if bd:
        refusals = bd.get("refusals_by_level", {})
        lines.append(
            f"budget dir  : {bd.get('shards', 0)} shards   refusals "
            + "  ".join(f"{lvl}={refusals.get(lvl, 0)}"
                        for lvl in ("user", "party", "global")))
    return "\n".join(lines)


def run_stream_top(url: str, interval_s: float = 2.0,
                   once: bool = False, out=None,
                   max_frames: int | None = None) -> int:
    """The ``dpcorr obs top --stream`` loop — same scrape/retry/exit
    contract as :func:`run_top`, rendering the stream frame."""
    emit = out if out is not None else print
    frames = 0
    while True:
        try:
            polled = scrape(url)
        except (urllib.error.URLError, ValueError, OSError) as e:
            if frames == 0:
                emit(f"obs top: cannot scrape {url}: {e}")
                return 1
            emit(f"obs top: scrape failed ({e}); retrying")
            time.sleep(interval_s)
            continue
        frame = render_stream_frame(polled["stats"], polled["metrics"])
        if once:
            emit(frame)
            return 0
        emit(_CLEAR + frame)
        frames += 1
        if max_frames is not None and frames >= max_frames:
            return 0
        time.sleep(interval_s)


def run_top(url: str, interval_s: float = 2.0, once: bool = False,
            out=None, max_frames: int | None = None) -> int:
    """The ``dpcorr obs top`` loop. Returns a process exit code: 0 on
    any successful frame, 1 when the first scrape fails (the CI smoke
    treats an unreachable server as a failure, not a hang)."""
    emit = out if out is not None else print
    frames = 0
    while True:
        try:
            polled = scrape(url)
        except (urllib.error.URLError, ValueError, OSError) as e:
            if frames == 0:
                emit(f"obs top: cannot scrape {url}: {e}")
                return 1
            emit(f"obs top: scrape failed ({e}); retrying")
            time.sleep(interval_s)
            continue
        frame = render_frame(polled["stats"], polled["metrics"])
        if once:
            emit(frame)
            return 0
        emit(_CLEAR + frame)
        frames += 1
        if max_frames is not None and frames >= max_frames:
            return 0
        time.sleep(interval_s)
