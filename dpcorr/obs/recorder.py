"""Flight recorder: bounded in-memory rings, dumped atomically on failure.

A breaker trip, brownout latch or chaos kill used to leave only coarse
counters behind; the question an operator actually asks — *what was the
server doing in the seconds before it went wrong* — needs the recent
spans, budget events, log lines and metric values in ONE artifact. The
recorder keeps exactly that, always on and bounded:

- four rings (``collections.deque(maxlen=...)`` under one lock): recent
  **spans** (fed by a tracer observer — obs.trace), **audit events**
  (fed by an AuditTrail observer — obs.audit), **log lines** (a
  ``logging.Handler`` attached to the ``dpcorr`` logger tree) and
  **metric samples** (explicit :meth:`sample` calls plus one final
  sample at dump time, over every watched registry);
- the server's :class:`~dpcorr.obs.cost.CostRegistry` is folded into
  every dump, so the artifact carries each recent request's CostRecord
  next to its spans;
- :meth:`dump` writes one strict-JSON document atomically — tmp file,
  flush, fsync, ``os.replace`` — the same crash-safe publish the ledger
  and the protocol journal use, so a dump racing a kill is either fully
  there or absent, never truncated.

Dump triggers (all call :func:`trigger` on the installed recorder):
chaos crash points (``chaos.on_crash`` — the hook fires *before*
``os._exit``), circuit-breaker trips and brownout enter/exit
(serve.overload callbacks), unhandled coalescer flush exceptions,
party-session failures, ``SIGUSR2`` (wired by ``dpcorr serve``) and the
``dpcorr obs dump`` CLI, which also replays an existing dump jax-free:
:func:`reconstruct` rebuilds one request's span chain, cost record and
ε trail from the artifact alone.

jax-free and import-light on purpose — the coalescer, chaos module and
CLI all import this, including under jax-free paths.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

DUMP_VERSION = 1

#: every trigger reason the recorder stamps — append-only by
#: convention, like chaos.KNOWN_POINTS (dashboards key on these)
TRIGGER_REASONS = (
    "chaos",               # a chaos crash point fired (pre-kill hook)
    "breaker_open",        # a bucket's circuit breaker tripped
    "brownout_enter",
    "brownout_exit",
    "coalescer_unhandled",  # the flush loop caught an unexpected error
    "party_unhandled",     # a protocol session died on an exception
    "sigusr2",             # operator asked (kill -USR2)
    "cli",                 # dpcorr obs dump --live / tests
    "shutdown",            # orderly close with --flight-recorder armed
    "slo_page",            # a burn-rate page armed this instance (obs.slo)
    "federation_unhandled",       # a federation party died unexpectedly
    "federation_resume_refused",  # a pair link's resume handshake refused
    "federation_scan_violation",  # cross-pair scan / provenance divergence
    "stream_release_failed",      # a charged window's release raised
    "sentinel_violation",         # the live invariant sentinel caught
                                  # an ε/durability break (obs.sentinel)
)


class FlightRecorder:
    """Bounded always-on capture + atomic crash dump.

    ``path`` is where :meth:`dump` publishes (each dump atomically
    replaces it — the newest incident wins, and a half-written file is
    impossible by construction). ``capacity`` bounds every ring
    independently, so a span storm cannot evict the audit trail.
    """

    def __init__(self, path: str, capacity: int = 2048):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.path = path
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._spans: deque[dict] = deque(maxlen=capacity)  # guarded by: _lock
        self._audit: deque[dict] = deque(maxlen=capacity)  # guarded by: _lock
        self._logs: deque[dict] = deque(maxlen=capacity)  # guarded by: _lock
        self._samples: deque[dict] = deque(maxlen=max(capacity // 8, 8))  # guarded by: _lock
        self._dumps = 0  # guarded by: _lock
        self._reasons: list[str] = []  # guarded by: _lock
        self._registries: list = []  # guarded by: _lock
        self._costs = None  # guarded by: _lock (CostRegistry | None)
        self._log_handler: logging.Handler | None = None

    # -- capture hooks ---------------------------------------------------
    def record_span(self, span: dict) -> None:
        """Tracer observer (obs.trace.Tracer.add_observer)."""
        with self._lock:
            self._spans.append(span)

    def record_audit(self, event: dict) -> None:
        """Audit observer (obs.audit.AuditTrail.add_observer)."""
        with self._lock:
            self._audit.append(event)

    def record_log(self, entry: dict) -> None:
        with self._lock:
            self._logs.append(entry)

    def watch_registry(self, registry) -> None:
        """Include ``registry`` (obs.metrics.Registry) in every metric
        sample and in the final snapshot a dump takes."""
        with self._lock:
            if registry not in self._registries:
                self._registries.append(registry)

    def watch_costs(self, costs) -> None:
        """Fold ``costs`` (obs.cost.CostRegistry) into every dump."""
        with self._lock:
            self._costs = costs

    def sample(self, label: str = "") -> None:
        """Append one timestamped metric sample (flat series → value
        over every watched registry) to the sample ring."""
        snap = self._metrics_now()
        with self._lock:
            self._samples.append({"ts": time.time(), "label": label,
                                  "values": snap})

    def _metrics_now(self) -> dict[str, float]:
        with self._lock:
            registries = list(self._registries)
        out: dict[str, float] = {}
        for reg in registries:
            for m in reg.metrics():
                for name, labels, value in m.samples():
                    out[f"{name}{labels}"] = value
        return out

    def logging_handler(self) -> logging.Handler:
        """A ``logging.Handler`` that feeds the log ring — attach it to
        the ``dpcorr`` logger tree (``attach_logging``)."""
        if self._log_handler is None:
            self._log_handler = _RingHandler(self)
        return self._log_handler

    def attach_logging(self, logger_name: str = "dpcorr") -> None:
        logging.getLogger(logger_name).addHandler(self.logging_handler())

    def detach_logging(self, logger_name: str = "dpcorr") -> None:
        if self._log_handler is not None:
            logging.getLogger(logger_name).removeHandler(self._log_handler)

    # -- dumping ---------------------------------------------------------
    def snapshot(self, reason: str, **detail) -> dict:
        """The dump document (also what tests assert on without I/O)."""
        metrics = self._metrics_now()
        with self._lock:
            costs = self._costs
            doc = {
                "version": DUMP_VERSION,
                "reason": reason,
                "ts": time.time(),
                "detail": {k: v for k, v in detail.items()},
                "spans": list(self._spans),
                "audit": list(self._audit),
                "logs": list(self._logs),
                "metric_samples": list(self._samples),
                "metrics": metrics,
            }
        doc["costs"] = costs.to_dict() if costs is not None else {}
        return doc

    def dump(self, reason: str, **detail) -> str:
        """Publish the current rings atomically to ``self.path`` and
        return the path. Crash-safe by the ledger's own pattern: write
        to a pid-suffixed tmp file, flush, fsync, ``os.replace`` — a
        reader never observes a partial document."""
        doc = self.snapshot(reason, **detail)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, default=_json_fallback)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        with self._lock:
            self._dumps += 1
            self._reasons.append(reason)
        return self.path

    @property
    def dumps(self) -> int:
        with self._lock:
            return self._dumps

    @property
    def reasons(self) -> list[str]:
        """Every dump reason so far, oldest first (the file on disk
        only keeps the newest incident — gates check history here)."""
        with self._lock:
            return list(self._reasons)

    @property
    def last_reason(self) -> str | None:
        with self._lock:
            return self._reasons[-1] if self._reasons else None


class _RingHandler(logging.Handler):
    """Feeds formatted log records into the recorder's log ring."""

    def __init__(self, recorder: FlightRecorder):
        super().__init__()
        self._recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._recorder.record_log({
                "ts": record.created,
                "level": record.levelname,
                "logger": record.name,
                "message": record.getMessage(),
            })
        except Exception:  # a dying log path must never take down the app
            pass


def _json_fallback(obj):
    """Dump rings may hold numpy scalars (span attrs); render them as
    plain floats/strings rather than failing the one artifact a crash
    leaves behind."""
    try:
        return float(obj)
    except (TypeError, ValueError):
        return repr(obj)


# ------------------------------------------------- process-wide install ----
_install_lock = threading.Lock()
_active: FlightRecorder | None = None


def install(recorder: FlightRecorder | None) -> None:
    """Make ``recorder`` the process recorder :func:`trigger` dumps to
    (``None`` disarms). The serving/protocol layers call ``trigger``
    through this indirection so they stay importable — and zero-cost —
    when no recorder is armed."""
    global _active
    with _install_lock:
        _active = recorder


def active() -> FlightRecorder | None:
    return _active


def trigger(reason: str, **detail) -> str | None:
    """Dump the installed recorder (no-op without one). Never raises:
    the trigger sites are failure paths — a broken dump must not mask
    the original incident."""
    rec = _active
    if rec is None:
        return None
    try:
        return rec.dump(reason, **detail)
    except Exception:
        logging.getLogger("dpcorr.obs").exception(
            "flight-recorder dump failed (reason=%s)", reason)
        return None


# ------------------------------------------------------ reading dumps ----
def read_dump(path: str) -> dict:
    """Load a flight-recorder dump strictly: one JSON document with the
    required keys, version-checked — the CI artifact gate wants a
    truncated or hand-edited dump to fail loudly, not parse as empty."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: dump is not a JSON object")
    if doc.get("version") != DUMP_VERSION:
        raise ValueError(f"{path}: dump version {doc.get('version')!r}, "
                         f"expected {DUMP_VERSION}")
    for key in ("reason", "ts", "spans", "audit", "logs", "metrics",
                "costs"):
        if key not in doc:
            raise ValueError(f"{path}: dump missing key {key!r}")
    return doc


def reconstruct(dump: dict, trace_id: str) -> dict:
    """Rebuild one request's story from a dump, jax-free: its span
    chain (parent-linked, admission order), its cost record, its audit
    events, and the ε net of those events (charges minus refunds,
    clamped — the ledger's arithmetic via obs.audit.replay). This is
    what ``dpcorr obs dump --trace-id`` prints and what the CI
    end-to-end gate asserts on."""
    from dpcorr.obs.audit import replay

    spans = [sp for sp in dump.get("spans", ())
             if sp.get("trace_id") == trace_id]
    spans.sort(key=lambda sp: sp.get("ts", 0.0))
    audit = [ev for ev in dump.get("audit", ())
             if ev.get("trace_id") == trace_id]
    chain = _order_chain(spans)
    return {
        "trace_id": trace_id,
        "spans": chain,
        "cost": dump.get("costs", {}).get(trace_id),
        "audit": audit,
        "eps_net": replay(audit),
    }


def _order_chain(spans: list[dict]) -> list[dict]:
    """Root-first parent-before-child ordering of one trace's spans
    (stable on timestamp within a generation; orphans — parents evicted
    from the ring — surface after the rooted tree rather than being
    dropped)."""
    by_parent: dict = {}
    ids = {sp.get("span_id") for sp in spans}
    for sp in spans:
        parent = sp.get("parent_id")
        if parent not in ids:
            parent = None if parent is None else "__orphan__"
        by_parent.setdefault(parent, []).append(sp)
    out: list[dict] = []
    queue = list(by_parent.get(None, ()))
    while queue:
        sp = queue.pop(0)
        out.append(sp)
        queue.extend(by_parent.get(sp.get("span_id"), ()))
    out.extend(by_parent.get("__orphan__", ()))
    return out
