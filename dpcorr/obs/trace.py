"""Lightweight span tracer: JSONL log + Chrome trace-event export.

The repo had three disjoint timing paths — the serve-local latency
reservoir, the grid timings frame and ``jax.profiler`` dumps — with no
way to correlate a slow p99 with the compile or flush that caused it.
This tracer is the host-side spine joining them:

- a **span** is one named wall-clock interval with a ``trace_id``
  linking every span of one logical operation (a serve request, a grid
  run, an ε-sweep) and a ``parent_id`` giving the in-trace tree;
- spans land as one JSON object per line (append-only JSONL — crash
  leaves a valid prefix, ``tail -f`` works, and the summarizer in
  ``benchmarks/trace_summary.py`` reduces it);
- :func:`to_chrome_trace` converts a span log into Chrome trace-event
  format (``{"traceEvents": [...]}``), loadable directly in Perfetto /
  ``chrome://tracing`` next to the XLA dumps ``utils.profiling.trace``
  captures — host spans and device ops in one timeline.

Parenting is implicit within a thread (a context-local stack) and
explicit across threads: the serve admission path runs on client
threads while flushes run on the coalescer thread, so the request's
:class:`SpanContext` rides the pending queue and the flush thread
passes it as ``parent=`` (serve.coalescer).

A tracer constructed with ``path=None`` is disabled: ``span()`` yields
a reusable null span and touches no locks — instrumented code pays a
single attribute check when tracing is off. Device time is optional:
callers that fetch (block) inside a span can record the device-side
seconds as an attr (``span.set(device_s=...)``); the tracer never
forces a sync itself.
"""

from __future__ import annotations

import contextlib
import json
import os
import secrets
import threading
import time

_local = threading.local()


def _new_id() -> str:
    """64-bit random hex — unique far past any realistic span volume."""
    return secrets.token_hex(8)


class SpanContext:
    """The cross-thread handle: just (trace_id, span_id), picklable and
    cheap — what rides the coalescer's pending queue."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


class Span:
    """One live interval. ``set(**attrs)`` attaches attributes (device
    seconds, batch size, ε); ``end()`` stamps the duration and writes
    the JSONL line. Use via ``tracer.span(...)`` unless the begin/end
    points live on different call paths (the serve request root span
    ends on the flush thread) — then ``tracer.start_span``/``end``."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "attrs", "t_wall", "_t0", "_tid", "_ended")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str | None, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.attrs = attrs
        self.t_wall = time.time()
        self._t0 = time.perf_counter()
        self._tid = threading.current_thread().name
        self._ended = False

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self.tracer._write(self, time.perf_counter() - self._t0)


class _NullSpan:
    """The disabled tracer's span: every operation a no-op, one shared
    instance, so instrumentation costs nothing when tracing is off."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None
    context = None

    def set(self, **attrs) -> None:
        pass

    def end(self) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    """JSONL span writer. ``path=None`` disables (null spans) — unless
    an observer attaches (:meth:`add_observer`), which enables span
    production without a file so the flight recorder can capture spans
    on servers that never asked for a span log."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.enabled = path is not None
        self._lock = threading.Lock()
        self._fh = None  # guarded by: _lock
        self._observers: list = []  # guarded by: _lock
        if self.enabled:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(path, "a", buffering=1)  # line-buffered

    def add_observer(self, fn) -> None:
        """Register ``fn(span_dict)`` to receive every finished span
        (the flight recorder's span ring). Attaching enables the tracer
        even with no span file."""
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)
        self.enabled = True

    def remove_observer(self, fn) -> None:
        with self._lock:
            if fn in self._observers:
                self._observers.remove(fn)
            if self._fh is None and not self._observers:
                self.enabled = False

    def start_span(self, name: str, parent: SpanContext | Span | None = None,
                   trace_id: str | None = None, **attrs) -> Span:
        """Begin a span the caller will ``end()`` explicitly. Parent
        resolution order: explicit ``parent``, else the calling thread's
        current span, else a fresh root (new trace unless ``trace_id``
        pins one)."""
        if not self.enabled:
            return _NULL_SPAN
        if parent is not None:
            return Span(self, name, parent.trace_id, parent.span_id, attrs)
        cur = current_span()
        if cur is not None and cur.tracer is self:
            return Span(self, name, cur.trace_id, cur.span_id, attrs)
        return Span(self, name, trace_id or _new_id(), None, attrs)

    @contextlib.contextmanager
    def span(self, name: str, parent: SpanContext | Span | None = None,
             trace_id: str | None = None, **attrs):
        """``with tracer.span("grid.fetch", n=4000) as sp:`` — ends on
        exit (errors too, stamped ``error=<type>``), and maintains the
        thread's implicit-parent stack."""
        sp = self.start_span(name, parent=parent, trace_id=trace_id,
                             **attrs)
        if sp is _NULL_SPAN:
            yield sp
            return
        stack = _span_stack()
        stack.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.set(error=type(e).__name__)
            raise
        finally:
            stack.pop()
            sp.end()

    def _write(self, sp: Span, dur_s: float) -> None:
        obj = {
            "name": sp.name, "trace_id": sp.trace_id,
            "span_id": sp.span_id, "parent_id": sp.parent_id,
            "ts": sp.t_wall, "dur_s": dur_s, "thread": sp._tid,
            "attrs": sp.attrs,
        }
        with self._lock:
            if self._fh is not None:
                self._fh.write(json.dumps(obj) + "\n")
            observers = list(self._observers)
        # observers run outside the tracer lock: the recorder takes its
        # own ring lock and must not nest under ours
        for fn in observers:
            fn(obj)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            if not self._observers:
                self.enabled = False


def _span_stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current_span() -> Span | None:
    """The calling thread's innermost live span (implicit parent)."""
    st = getattr(_local, "stack", None)
    return st[-1] if st else None


# ------------------------------------------------------- global tracer ----
_global = Tracer(None)
_global_lock = threading.Lock()


def configure(path: str | None) -> Tracer:
    """Install the process tracer (CLI ``--trace`` / DPCORR_TRACE env).
    ``None`` reverts to disabled. Returns the new tracer."""
    global _global
    with _global_lock:
        old, _global = _global, Tracer(path)
        if old.enabled:
            old.close()
        return _global


def tracer() -> Tracer:
    """The process tracer — disabled unless :func:`configure` (or the
    ``DPCORR_TRACE`` env var, read once at first use) enabled it."""
    global _global
    if not _global.enabled:
        env = os.environ.get("DPCORR_TRACE")
        if env:
            with _global_lock:
                if not _global.enabled:
                    _global = Tracer(env)
    return _global


# ---------------------------------------------------- wire propagation ----
def wire_headers(ctx: SpanContext | Span | None) -> dict[str, str]:
    """Serialize a span context into message headers, so one trace can
    cover both protocol processes (dpcorr.protocol): the sender stamps
    its current span here, the receiver parents its own spans on
    :func:`from_wire_headers` of what arrived. Returns ``{}`` when
    tracing is off (null span / ``None``) — absent headers, not empty
    strings, so the receiving side stays a clean root."""
    if ctx is None or ctx.trace_id is None:
        return {}
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


def from_wire_headers(headers: dict | None) -> SpanContext | None:
    """Inverse of :func:`wire_headers`: rebuild the remote parent
    context from message headers, ``None`` when the peer wasn't
    tracing."""
    if not headers:
        return None
    tid, sid = headers.get("trace_id"), headers.get("span_id")
    if not tid or not sid:
        return None
    return SpanContext(str(tid), str(sid))


# ------------------------------------------------------ readers/export ----
def read_spans(path: str) -> list[dict]:
    """Load a JSONL span log; raises ValueError naming the first bad
    line (the CI gate wants unparseable to fail loudly)."""
    spans = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: bad span line: {e}") from e
            if not isinstance(obj, dict) or "name" not in obj \
                    or "dur_s" not in obj:
                raise ValueError(f"{path}:{i}: not a span object")
            spans.append(obj)
    return spans


def to_chrome_trace(spans: list[dict] | str) -> dict:
    """Convert a span log (list or JSONL path) into Chrome trace-event
    JSON — ``X`` (complete) events, microsecond timestamps, one ``tid``
    row per originating thread. Load the result in Perfetto or
    ``chrome://tracing``; span attrs (and trace/span ids) appear as
    event ``args`` so a request chain is clickable."""
    if isinstance(spans, str):
        spans = read_spans(spans)
    tids: dict[str, int] = {}
    events = []
    for sp in spans:
        tid = tids.setdefault(sp.get("thread", "main"), len(tids) + 1)
        events.append({
            "name": sp["name"], "ph": "X", "pid": 1, "tid": tid,
            "ts": sp.get("ts", 0.0) * 1e6,
            "dur": sp["dur_s"] * 1e6,
            "args": {**sp.get("attrs", {}),
                     "trace_id": sp.get("trace_id"),
                     "span_id": sp.get("span_id"),
                     "parent_id": sp.get("parent_id")},
        })
    meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": t,
             "args": {"name": name}} for name, t in tids.items()]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome_trace(spans: list[dict] | str, out_path: str) -> str:
    with open(out_path, "w") as f:
        json.dump(to_chrome_trace(spans), f)
    return out_path
