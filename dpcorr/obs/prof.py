"""Low-overhead block-boundary profiler for the rep hot path (ISSUE 15).

The PR 6 pipeline (``sim.RepBlockPipeline``) keeps exactly one host sync
per ``run()`` — that invariant is why it beat baseline, and the ``sync``
lint rule defends it.  Profiling therefore cannot mean "sync every
block".  This profiler syncs only at a bounded *cadence*: with
``max_syncs=64`` and a 10,000-block run it blocks on the accumulator
every ~156 blocks, giving per-segment device timings at a cost that the
interleaved A/B in ``benchmarks/rep_pipeline_ab.py`` gates at ≤3% p50.

The unprofiled path pays nothing: ``RepBlockPipeline`` only touches the
profiler through ``if profiler is not None`` guards, and a run with no
profiler performs the same single sync it always did — the A/B proves
this with the PR 6 transfer counters (``fetches`` deltas are identical
with and without a constructed-but-inactive profiler).

Profiler syncs are counted in ``dpcorr_prof_syncs_total``, NOT in the
transfer ``fetches`` counter: ``fetches`` keeps meaning "results the
caller asked for", so the zero-extra-sync proof stays readable.

Module import must stay jax-free (``jax.block_until_ready`` is imported
lazily inside the sync) so the metric names and artifact readers are
usable from the jax-free CLI.
"""

from __future__ import annotations

import contextlib
import datetime as _dt
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from dpcorr.obs import metrics as metrics_mod

ENV_VAR = "DPCORR_PROF"
DEFAULT_MAX_SYNCS = 64
OVERHEAD_BUDGET_PCT = 3.0

# Per-segment device timings: a segment is cadence-many blocks, so
# spans run from sub-ms (tiny tests) to seconds (big cells).
PROF_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _utcnow() -> str:
    return _dt.datetime.now(_dt.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


class BlockProfiler:
    """Bounded-sync profiler folded with transfer counters and spans.

    One instance may observe many runs (the bench harness reuses one
    across repeats); per-run state lives in the dict ``run_start``
    returns, so concurrent pipelines can share a profiler.
    """

    def __init__(
        self,
        *,
        cadence: Optional[int] = None,
        max_syncs: int = DEFAULT_MAX_SYNCS,
        registry=None,
        artifact_path: Optional[str] = None,
        tracer=None,
    ) -> None:
        self.cadence = cadence
        self.max_syncs = max(1, int(max_syncs))
        self.artifact_path = artifact_path
        self._tracer = tracer
        self._lock = threading.Lock()
        self._runs: List[Dict[str, Any]] = []
        self._phases: List[Dict[str, Any]] = []
        reg = registry or metrics_mod.default_registry()
        self.runs_total = reg.counter(
            "dpcorr_prof_runs_total", "Profiled pipeline runs."
        )
        self.syncs_total = reg.counter(
            "dpcorr_prof_syncs_total",
            "Host syncs the profiler itself performed (cadence-bounded; "
            "never counted as transfer fetches).",
        )
        self.block_seconds = reg.histogram(
            "dpcorr_prof_block_seconds",
            "Per-block device seconds inferred from cadence segments.",
            buckets=PROF_BUCKETS,
        )
        self.last_rps = reg.gauge(
            "dpcorr_prof_last_reps_per_sec",
            "Throughput of the most recent profiled run.",
        )
        self.phase_seconds = reg.counter(
            "dpcorr_prof_phase_seconds_total",
            "Wall seconds spent per instrumented phase.",
            labelnames=("phase",),
        )

    # -- run lifecycle (called by RepBlockPipeline under `is not None`) --

    def run_start(
        self,
        *,
        family: str = "custom",
        block_reps: int = 0,
        n_blocks: int = 0,
        start_block: int = 0,
        counters=None,
    ) -> Dict[str, Any]:
        cadence = self.cadence
        if cadence is None:
            cadence = max(1, int(n_blocks) // self.max_syncs)
        now = time.perf_counter()
        return {
            "family": family,
            "block_reps": int(block_reps),
            "n_blocks": int(n_blocks),
            "start_block": int(start_block),
            "cadence": int(cadence),
            "t0": now,
            "t_last": now,
            "i_last": -1,
            "sync_count": 0,
            "samples": [],
            "counters": counters,
            "transfer_before": counters.snapshot() if counters is not None else None,
        }

    def block_boundary(self, state: Dict[str, Any], i: int, acc: Any) -> None:
        """Maybe sync at block ``i``; record a segment sample if we did."""
        if (i + 1) % state["cadence"] != 0:
            return
        import jax  # deferred: module import stays jax-free

        jax.block_until_ready(acc)
        now = time.perf_counter()
        blocks = i - state["i_last"]
        seconds = now - state["t_last"]
        state["t_last"] = now
        state["i_last"] = i
        state["sync_count"] += 1
        self.syncs_total.inc()
        state["samples"].append(
            {
                "block": int(i),
                "blocks": int(blocks),
                "seconds": seconds,
                "reps_per_sec": (
                    blocks * state["block_reps"] / seconds if seconds > 0 else 0.0
                ),
            }
        )
        if blocks > 0 and seconds > 0:
            self.block_seconds.observe(seconds / blocks)

    def run_end(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Close out a run: fold transfer deltas, emit span + artifact."""
        seconds = time.perf_counter() - state["t0"]
        reps = state["n_blocks"] * state["block_reps"]
        rps = reps / seconds if seconds > 0 else 0.0
        rec: Dict[str, Any] = {
            "family": state["family"],
            "start_block": state["start_block"],
            "n_blocks": state["n_blocks"],
            "block_reps": state["block_reps"],
            "cadence": state["cadence"],
            "seconds": seconds,
            "reps_per_sec": rps,
            "sync_count": state["sync_count"],
            "samples": state["samples"],
        }
        counters = state.get("counters")
        before = state.get("transfer_before")
        if counters is not None and before is not None:
            from dpcorr.obs import transfer as transfer_mod

            rec["transfer"] = transfer_mod.diff(counters.snapshot(), before)
        self.runs_total.inc()
        self.last_rps.set(rps)
        tr = self._tracer if self._tracer is not None else _trace_mod().tracer()
        sp = tr.start_span(
            "prof.run",
            family=state["family"],
            n_blocks=state["n_blocks"],
            block_reps=state["block_reps"],
            sync_count=state["sync_count"],
            reps_per_sec=round(rps, 3),
        )
        sp.end()
        with self._lock:
            self._runs.append(rec)
        if self.artifact_path:
            self.write_artifact(self.artifact_path)
        return rec

    # -- phase timing (grid.py scan/dispatch/fetch) --

    def note_phase(self, name: str, seconds: float, **attrs) -> None:
        """Record an already-timed phase (grid.py times its phases
        inline so the unprofiled path needs no context-manager frames)."""
        self.phase_seconds.inc(seconds, phase=name)
        rec = {"name": name, "seconds": float(seconds)}
        rec.update(attrs)
        with self._lock:
            self._phases.append(rec)

    @contextlib.contextmanager
    def phase(self, name: str, **attrs):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.note_phase(name, time.perf_counter() - t0, **attrs)

    # -- artifact --

    def as_artifact(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "kind": "dpcorr_profile",
                "runs": [dict(r) for r in self._runs],
                "phases": [dict(p) for p in self._phases],
                "captured_utc": _utcnow(),
            }

    def write_artifact(self, path: str) -> str:
        payload = self.as_artifact()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path


def _trace_mod():
    from dpcorr.obs import trace as trace_mod

    return trace_mod


# ---------------------------------------------------------------------------
# Process-wide activation (opt-in; nothing reads the env on the hot path)

_active: Optional[BlockProfiler] = None
_env_checked = False
_lock = threading.Lock()


def activate(profiler: Optional[BlockProfiler]) -> None:
    """Install (or clear, with None) the process profiler."""
    global _active, _env_checked
    with _lock:
        _active = profiler
        _env_checked = True


def active() -> Optional[BlockProfiler]:
    """The process profiler, initialized once from ``DPCORR_PROF``.

    Unset/0/off/false → None (the default, zero-cost path).  "1"/"true"/
    "on" → an artifact-less profiler.  Any other value is treated as the
    profile artifact path.
    """
    global _active, _env_checked
    with _lock:
        if not _env_checked:
            _env_checked = True
            raw = os.environ.get(ENV_VAR, "").strip()
            if raw and raw.lower() not in ("0", "off", "false", "none"):
                if raw.lower() in ("1", "true", "on"):
                    _active = BlockProfiler()
                else:
                    _active = BlockProfiler(artifact_path=raw)
        return _active


def phase(name: str, **attrs):
    """Module-level phase timer: nullcontext when no profiler is active."""
    prof = active()
    if prof is None:
        return contextlib.nullcontext()
    return prof.phase(name, **attrs)


def note_phase(name: str, seconds: float, **attrs) -> None:
    """Module-level pre-timed phase record: no-op when inactive."""
    prof = active()
    if prof is not None:
        prof.note_phase(name, seconds, **attrs)


# ---------------------------------------------------------------------------
# jax-free artifact reader (CI and tests consume the A/B verdict)


def read_profile(path: str) -> Dict[str, Any]:
    """Load a profile artifact; raises ValueError on bad shape."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("kind") != "dpcorr_profile":
        raise ValueError(f"{path}: not a dpcorr_profile artifact")
    return data
