"""Declarative SLOs evaluated as multi-window burn-rate alerts.

The serve layer already publishes a single-window burn gauge
(``dpcorr_serve_slo_burn_rate`` — one threshold, one window, one
process). Fleet operation needs the real thing: objectives declared
once, evaluated over the *scraped* cumulative series of every instance,
with the classic multi-window / multi-burn-rate policy (a page needs
BOTH a fast short-window burn and a sustained long-window burn, so a
single slow request cannot page and a slow leak cannot hide).

Everything here is deterministic and clock-injectable on purpose:
``observe``/``evaluate`` take an explicit ``at`` timestamp, so the
state machine's transitions are a pure function of the scraped counter
deltas and the scripted clock — the property the tests and the
``serve_load --fleet`` gate pin. No wall-clock reads happen unless the
caller omits ``at``.

Objective kinds (all computed from cumulative exposition series, so a
missed scrape loses resolution, never correctness):

- ``latency`` — a request is *bad* when it lands above ``threshold_s``
  in the instance's latency histogram. The threshold must be an exact
  bucket bound: cumulative buckets make "good ≤ le" exact, and refusing
  an off-bucket threshold loudly beats silently interpolating.
- ``error``   — bad = Σ configured failure counters (refused, failed),
  total = admitted + refused.
- ``eps_burn`` — bad = ε actually spent (from the scraped per-party
  spend series), budget = ``eps_per_s × window`` — "are we spending
  privacy budget faster than the release schedule sustains".

The ``page`` transition arms the offending instance's flight recorder
through its existing trigger hook: in-process via
:func:`recorder_trigger_hook` (→ ``obs.recorder.trigger("slo_page")``),
cross-process via :func:`http_trigger_hook` (→ ``POST /obs/trigger`` on
the serve front end, which calls the same hook inside that instance).
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Callable, Mapping

from dpcorr.obs.fleet import MetricFamily

#: classic multi-window policy (Google SRE workbook shape): page on a
#: fast, confirmed burn; warn on a sustained slow one. Windows are in
#: seconds of scraped history; thresholds are in "error budgets per
#: window" (burn rate 1.0 = spending exactly the allowed budget).
DEFAULT_WINDOWS = (
    # severity, short window, long window, burn-rate threshold
    ("page", 300.0, 3600.0, 14.4),
    ("warn", 1800.0, 21600.0, 6.0),
)

_KINDS = ("latency", "error", "eps_burn", "gauge")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective. ``target`` is the error budget — the
    tolerated bad fraction (latency/error) — or, for ``eps_burn``, the
    sustainable spend rate is ``eps_per_s`` and ``target`` scales it
    (1.0 = page when spending faster than the schedule itself)."""

    name: str
    kind: str
    target: float
    #: latency kind: histogram family + exact bucket bound
    histogram: str = "dpcorr_serve_latency_seconds"
    threshold_s: float | None = None
    #: error kind: family names summed into the denominator / numerator
    total_series: tuple = ("dpcorr_serve_requests_total",
                           "dpcorr_serve_requests_refused_total")
    bad_series: tuple = ("dpcorr_serve_requests_refused_total",
                         "dpcorr_serve_requests_failed_total")
    #: eps_burn kind: spend gauge family + sustainable rate
    eps_series: str = "dpcorr_ledger_spent_eps"
    eps_per_s: float = 0.0
    #: gauge kind: an instantaneous level (e.g. watermark lag) whose
    #: budget is ``threshold_s`` — burn rate is worst-in-window / budget
    gauge_series: str = "dpcorr_stream_watermark_lag_seconds"

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"objective {self.name!r}: unknown kind "
                             f"{self.kind!r} (one of {_KINDS})")
        if self.target <= 0:
            raise ValueError(f"objective {self.name!r}: target must be "
                             f"> 0, got {self.target}")
        if self.kind == "latency" and self.threshold_s is None:
            raise ValueError(f"objective {self.name!r}: latency kind "
                             f"needs threshold_s")
        if self.kind == "eps_burn" and self.eps_per_s <= 0:
            raise ValueError(f"objective {self.name!r}: eps_burn kind "
                             f"needs eps_per_s > 0")
        if self.kind == "gauge" and (self.threshold_s is None
                                     or self.threshold_s <= 0):
            raise ValueError(f"objective {self.name!r}: gauge kind "
                             f"needs threshold_s > 0 (the level budget)")

    # -- cumulative (bad, total) off one instance's parsed families ----
    def cumulative(self, families: Mapping[str, MetricFamily],
                   ) -> tuple[float, float | None]:
        """``(bad, total)`` as cumulative values; ``total`` is ``None``
        for ``eps_burn`` (its budget is a rate × window, not a scraped
        counter)."""
        if self.kind == "latency":
            fam = families.get(self.histogram)
            if fam is None:
                return 0.0, 0.0
            total = _sum_samples(fam, f"{self.histogram}_count")
            good = None
            want = _le_repr(self.threshold_s)
            for sample_name, labels, value in fam.samples:
                if sample_name != f"{self.histogram}_bucket":
                    continue
                le = dict(labels).get("le")
                if le is not None and _le_match(le, want):
                    good = (good or 0.0) + value
            if good is None:
                les = sorted({dict(ls).get("le")
                              for s, ls, _ in fam.samples
                              if s == f"{self.histogram}_bucket"})
                raise ValueError(
                    f"objective {self.name!r}: threshold_s="
                    f"{self.threshold_s} is not a bucket bound of "
                    f"{self.histogram} (le ∈ {les}) — cumulative "
                    f"buckets only answer exact-bound questions")
            return total - good, total
        if self.kind == "error":
            total = sum(_sum_samples(families.get(n)) or 0.0
                        for n in self.total_series)
            bad = sum(_sum_samples(families.get(n)) or 0.0
                      for n in self.bad_series)
            return bad, total
        if self.kind == "gauge":
            # a level, not a rate: "bad" is the gauge itself (worst
            # sample when labelled), and there is no denominator
            fam = families.get(self.gauge_series)
            if fam is None:
                return 0.0, None
            vals = [v for _n, _ls, v in fam.samples]
            return (max(vals) if vals else 0.0), None
        # eps_burn: cumulative spend over every party the series carries
        fam = families.get(self.eps_series)
        return (_sum_samples(fam) or 0.0), None


def _sum_samples(fam: MetricFamily | None,
                 sample_name: str | None = None) -> float | None:
    if fam is None:
        return None
    name = sample_name if sample_name is not None else fam.name
    return sum(v for s, _, v in fam.samples if s == name)


def _le_repr(bound: float) -> str:
    v = float(bound)
    return str(int(v)) if v.is_integer() else repr(v)


def _le_match(le: str, want: str) -> bool:
    if le == want:
        return True
    try:
        return float(le) == float(want) and not math.isinf(float(le))
    except ValueError:
        return False


@dataclasses.dataclass(frozen=True)
class Alert:
    """One state transition of one (objective, instance) pair."""

    objective: str
    instance: str
    severity: str          # "page" | "warn" | "ok"
    previous: str
    burn_short: float
    burn_long: float
    window: tuple          # the (severity, short_s, long_s, threshold) row
    at: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class BurnRateEngine:
    """The deterministic multi-window burn-rate state machine.

    Feed it scrapes with :meth:`observe` (cumulative families per
    instance, stamped by the injectable clock), then :meth:`evaluate`
    computes each (objective, instance) pair's burn rate over every
    configured window and walks the ``ok → warn → page`` machine.
    Transitions *into* ``page``/``warn`` fire ``on_page``/``on_warn``
    exactly once per transition — the page hook is how the offending
    instance's flight recorder gets armed.
    """

    def __init__(self, objectives, windows=DEFAULT_WINDOWS,
                 clock: Callable[[], float] | None = None,
                 on_page: Callable[[Alert], None] | None = None,
                 on_warn: Callable[[Alert], None] | None = None,
                 max_samples: int = 4096):
        self.objectives = tuple(objectives)
        if not self.objectives:
            raise ValueError("BurnRateEngine needs at least one objective")
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.windows = tuple(windows)
        self._clock = clock if clock is not None else time.monotonic
        self.on_page = on_page
        self.on_warn = on_warn
        self._series: dict[tuple, deque] = {}
        self._state: dict[tuple, str] = {}
        self._max = int(max_samples)
        #: every transition ever fired, oldest first (the artifact trail)
        self.alerts: list[Alert] = []

    # -- feeding -------------------------------------------------------
    def observe(self, families_by_instance: Mapping[str, Mapping],
                at: float | None = None) -> None:
        """Record one scrape: ``{instance: parsed families}`` (what
        ``FleetSnapshot.families()`` returns) at clock time ``at``."""
        t = float(at) if at is not None else self._clock()
        for inst in sorted(families_by_instance):
            fams = families_by_instance[inst]
            for obj in self.objectives:
                bad, total = obj.cumulative(fams)
                ring = self._series.setdefault(
                    (obj.name, inst), deque(maxlen=self._max))
                ring.append((t, bad, total))

    # -- burn arithmetic ----------------------------------------------
    def _burn(self, obj: Objective, ring, t: float,
              window_s: float) -> float:
        """Burn rate over the trailing ``window_s`` at time ``t``: the
        newest sample at or before ``t - window_s`` anchors the delta
        (falling back to the oldest sample — a partial window reads as
        what it is, not as zero)."""
        if len(ring) < 2:
            return 0.0
        if obj.kind == "gauge":
            # a gauge has no delta arithmetic: its burn over a window
            # is the worst level observed in [t - window_s, t] as a
            # multiple of the budget (threshold_s × target)
            worst = max((bad for ts, bad, _total in ring
                         if ts >= t - window_s),
                        default=ring[-1][1])
            budget = (obj.threshold_s or 0.0) * obj.target
            return worst / budget if budget > 0 else 0.0
        newest = ring[-1]
        anchor = ring[0]
        for sample in ring:
            if sample[0] <= t - window_s:
                anchor = sample
            else:
                break
        dt = newest[0] - anchor[0]
        if dt <= 0:
            return 0.0
        dbad = newest[1] - anchor[1]
        if obj.kind == "eps_burn":
            budget = obj.eps_per_s * dt * obj.target
            return max(0.0, dbad) / budget if budget > 0 else 0.0
        dtotal = (newest[2] or 0.0) - (anchor[2] or 0.0)
        if dtotal <= 0:
            return 0.0
        return (max(0.0, dbad) / dtotal) / obj.target

    # -- evaluation ----------------------------------------------------
    def evaluate(self, at: float | None = None) -> list[Alert]:
        """Walk every (objective, instance) pair's state machine at
        clock time ``at``; returns the transitions that fired (empty
        when nothing changed — re-evaluating an unchanged world is a
        no-op, which is what makes page delivery exactly-once)."""
        t = float(at) if at is not None else self._clock()
        fired: list[Alert] = []
        for (obj_name, inst), ring in sorted(self._series.items()):
            obj = next(o for o in self.objectives if o.name == obj_name)
            severity, burns, window = "ok", (0.0, 0.0), None
            for row in self.windows:
                row_sev, short_s, long_s, threshold = row
                b_short = self._burn(obj, ring, t, short_s)
                b_long = self._burn(obj, ring, t, long_s)
                if b_short > threshold and b_long > threshold:
                    severity, burns, window = row_sev, (b_short, b_long), row
                    break  # windows are ordered page-first
            prev = self._state.get((obj_name, inst), "ok")
            if severity == prev:
                continue
            self._state[(obj_name, inst)] = severity
            alert = Alert(objective=obj_name, instance=inst,
                          severity=severity, previous=prev,
                          burn_short=burns[0], burn_long=burns[1],
                          window=window if window is not None
                          else self.windows[0], at=t)
            self.alerts.append(alert)
            fired.append(alert)
            if severity == "page" and self.on_page is not None:
                self.on_page(alert)
            elif severity == "warn" and self.on_warn is not None:
                self.on_warn(alert)
        return fired

    def state(self, objective: str, instance: str) -> str:
        return self._state.get((objective, instance), "ok")

    def states(self) -> dict[str, str]:
        return {f"{o}/{i}": s for (o, i), s in sorted(self._state.items())}


# ------------------------------------- federation objectives (ISSUE 13) ----
def federation_round_latency_objective(
        name: str = "fed-round-latency", threshold_s: float = 2.5,
        target: float = 0.05) -> Objective:
    """Round-trip latency objective over a federation party's
    ``dpcorr_federation_round_latency_seconds`` histogram: a round is
    *bad* above ``threshold_s`` (which must be an exact
    ``LATENCY_BUCKETS`` bound), ``target`` is the tolerated bad
    fraction. Feed the party scrapes (``--obs-port``) to a
    :class:`BurnRateEngine` with :func:`http_trigger_hook` pointed at
    the same ports and a page dumps the *offending party's* flight
    recorder, in-process."""
    return Objective(
        name=name, kind="latency", target=target,
        histogram="dpcorr_federation_round_latency_seconds",
        threshold_s=threshold_s)


def federation_eps_burn_objectives(plan, makespan_s: float,
                                   target: float = 1.0) -> tuple:
    """One ε-burn-vs-plan-share objective per federation party: party
    P's sustainable rate is its :meth:`FederationPlan.party_eps` share
    spread over ``makespan_s`` (the matrix duration the schedule is
    sized for), so burn rate 1.0 means "spending exactly the plan
    share, on schedule" and a party re-charging artifacts or running
    ahead of plan pages. Each party process only exposes its *own*
    ``dpcorr_federation_ledger_spent_eps`` gauge, so evaluate each
    objective against its matching instance — pair alerts on
    ``alert.objective.endswith(alert.instance)`` or run one engine per
    party."""
    if makespan_s <= 0:
        raise ValueError(f"makespan_s must be > 0, got {makespan_s}")
    shares = plan.party_eps()
    return tuple(
        Objective(name=f"fed-eps-burn-{party}", kind="eps_burn",
                  target=target,
                  eps_series="dpcorr_federation_ledger_spent_eps",
                  eps_per_s=shares[party] / makespan_s)
        for party, _cols in plan.parties if shares[party] > 0)


# --------------------------------------------- stream objectives ----
def stream_release_latency_objective(
        name: str = "stream-release-latency", threshold_s: float = 1.0,
        target: float = 0.05) -> Objective:
    """Release-latency objective over a stream instance's
    ``dpcorr_stream_release_seconds`` histogram: a window release is
    *bad* above ``threshold_s`` (which must be an exact
    ``LATENCY_BUCKETS`` bound — cumulative buckets only answer
    exact-bound questions), ``target`` the tolerated bad fraction.
    Scrape the stream's ``--obs-port`` into the same
    :class:`BurnRateEngine` as serve and federation; a page through
    :func:`http_trigger_hook` dumps the stream's own flight
    recorder."""
    return Objective(
        name=name, kind="latency", target=target,
        histogram="dpcorr_stream_release_seconds",
        threshold_s=threshold_s)


def stream_watermark_lag_objective(
        name: str = "stream-watermark-lag", max_lag_s: float = 30.0,
        target: float = 1.0) -> Objective:
    """Freshness objective over ``dpcorr_stream_watermark_lag_seconds``
    (the gauge :mod:`dpcorr.stream.service` publishes alongside the
    absolute watermark — lag, not position, is what an SLO can
    threshold). ``max_lag_s × target`` is the lag *budget*: the burn
    rate is the worst lag observed in each evaluation window divided
    by that budget, so with the default multi-window thresholds a page
    means the watermark sustained ≥14.4× its budget in both windows —
    size ``max_lag_s`` as the budget, not as the page line."""
    return Objective(
        name=name, kind="gauge", target=target, threshold_s=max_lag_s,
        gauge_series="dpcorr_stream_watermark_lag_seconds")


# ------------------------------------------------- recorder arming ----
def recorder_trigger_hook(**extra) -> Callable[[Alert], None]:
    """In-process page hook: dump the installed flight recorder with
    reason ``slo_page`` (the recorder's existing trigger indirection —
    a no-op when none is armed, like every other trigger site)."""
    def hook(alert: Alert) -> None:
        from dpcorr.obs import recorder as obs_recorder

        obs_recorder.trigger("slo_page", objective=alert.objective,
                             instance=alert.instance,
                             burn_short=alert.burn_short,
                             burn_long=alert.burn_long, **extra)
    return hook


def http_trigger_hook(urls: Mapping[str, str],
                      timeout_s: float = 5.0) -> Callable[[Alert], None]:
    """Cross-process page hook for the fleet collector: POST the page
    to the *offending* instance's ``/obs/trigger`` endpoint, which
    calls that process's own ``recorder.trigger("slo_page", ...)`` —
    the dump happens inside the instance, next to its rings. Never
    raises (an unreachable instance is already the incident)."""
    def hook(alert: Alert) -> None:
        base = urls.get(alert.instance)
        if base is None:
            return
        body = json.dumps({
            "reason": "slo_page",
            "detail": {"objective": alert.objective,
                       "instance": alert.instance,
                       "burn_short": alert.burn_short,
                       "burn_long": alert.burn_long},
        }).encode()
        req = urllib.request.Request(
            f"{base.rstrip('/')}/obs/trigger", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s):
                pass
        except (urllib.error.URLError, OSError):
            pass
    return hook
