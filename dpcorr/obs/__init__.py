"""Unified telemetry layer (ISSUE 2): spans, metrics, budget audit.

Before this package, the repo had three disjoint reporting paths — the
serve-local ``ServeStats`` JSON blob, the grid driver's timings frame,
and raw ``jax.profiler`` dumps — none of which could correlate a slow
p99 with the compile storm or budget refusal that caused it. The obs
package is the one spine they now share:

- :mod:`trace`   — span tracer: context-manager API, trace/span IDs,
  JSONL log, Chrome trace-event export (Perfetto-viewable). The serve
  request lifecycle, the grid driver's dispatch/fetch phases and
  ``hrs.eps_sweep`` are instrumented with it.
- :mod:`metrics` — process-wide registry (counters, gauges, bucketed
  histograms) with Prometheus text exposition; ``ServeStats``, the
  kernel cache and the ledger publish through it, and the HTTP server
  serves it at ``GET /metrics``.
- :mod:`audit`   — the privacy-budget audit trail: every ledger
  charge/refund/refusal as a structured event carrying the request's
  trace ID; ``python -m dpcorr obs budget`` replays it into the
  per-party ε-spend timeline.
- :mod:`recorder` — the flight recorder (ISSUE 9): bounded in-memory
  rings of recent spans, audit events, log lines and metric samples,
  dumped atomically on crash points, breaker trips, brownout
  transitions, SIGUSR2 and ``dpcorr obs dump`` — replayable jax-free.
- :mod:`cost`    — per-request cost attribution: the CostRecord each
  admission accumulates (queue/compile/kernel seconds, retries, shed
  events, ε charged/refunded per party) plus the exemplar store that
  links latency-histogram buckets to trace IDs.
- :mod:`console` — the live ops console behind ``dpcorr obs top``:
  a jax-free terminal view over ``/metrics`` + ``/stats``.
- :mod:`fleet`   — the fleet telemetry plane (ISSUE 11): a pull-based
  collector over N instances, kind-aware exposition merging under
  ``instance`` labels (counters sum, histogram buckets add, collisions
  refuse loudly), span-spool union into one Chrome trace and audit
  union into one binary-exact fleet ε replay.
- :mod:`slo`     — declarative latency/error/ε-burn objectives
  evaluated as deterministic multi-window burn-rate alerts over the
  scraped series; the ``page`` transition arms the offending
  instance's flight recorder through its existing trigger hook.
- :mod:`devicemon` — per-device memory watermarks + transfer counters
  split per device, published as ``dpcorr_device_*`` gauges and
  stamped into bench artifacts.
- :mod:`provenance` — the federation ε-provenance DAG (ISSUE 13):
  per-party transcripts + audit trails + journals merged into
  artifacts → charges → rounds → cells, structurally proving
  exactly-once charging and byte-identical reuse at the
  ``2·f·ε·(k−1)`` optimum; typed divergences name the offending
  party. ``dpcorr obs provenance`` exports JSON + DOT, jax-free.
- :mod:`prof`    — the performance observability plane's hot-path half
  (ISSUE 15): a cadence-bounded block-boundary profiler for
  ``sim.RepBlockPipeline`` and the grid phases — per-segment device
  timings via at most ``max_syncs`` host syncs per run (never any in
  the unprofiled path), folded with the transfer counters into
  ``dpcorr_prof_*`` metrics, spans and a per-run profile artifact;
  gated at ≤3% p50 overhead by ``benchmarks/rep_pipeline_ab.py``.
- :mod:`hlo`     — compile-time introspection riding ``utils/compile``:
  per-signature ``cost_analysis`` (FLOPs, bytes), memory analysis, HLO
  fingerprints and op histograms, persisted as signature dumps that
  ``dpcorr obs hlo diff`` compares jax-free to explain layout/reshard
  boundaries and recompiles.
- :mod:`trajectory` — the bench-trajectory regression engine: the
  committed ``BENCH_*``/``MULTICHIP_*``/``benchmarks/results``
  artifacts normalized into per-(device_kind, metric) series; names
  the FIRST artifact that bent the curve (wired into ``bench.py
  --gate`` attribution and ``dpcorr obs trajectory``), jax-free.
- :mod:`endpoint` — the mini scrape surface for non-serve processes
  (``dpcorr federation party --obs-port``): ``/metrics`` + ``/stats``
  + ``POST /obs/trigger``, byte-compatible with serve's routes so the
  fleet collector, ``obs top`` and SLO paging work unchanged.

See docs/OBSERVABILITY.md for the span model, metric names and the
audit-trail format.
"""

from dpcorr.obs.audit import (  # noqa: F401
    AuditTrail,
    read_events,
    replay,
    timeline,
)
from dpcorr.obs.cost import (  # noqa: F401
    CostRecord,
    CostRegistry,
    ExemplarStore,
    split_exact,
)
from dpcorr.obs.endpoint import (  # noqa: F401
    make_obs_server,
    start_obs_server,
)
from dpcorr.obs.fleet import (  # noqa: F401
    FleetCollector,
    FleetSnapshot,
    MetricFamily,
    aggregate_families,
    fleet_chrome_trace,
    fleet_replay,
    merge_families,
    parse_families,
    render_families,
)
from dpcorr.obs.hlo import (  # noqa: F401
    HloStore,
    diff_dumps,
    load_dump,
    render_diff,
)
from dpcorr.obs.metrics import (  # noqa: F401
    CONTENT_TYPE,
    LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    parse_exposition,
)
from dpcorr.obs.prof import (  # noqa: F401
    BlockProfiler,
    read_profile,
)
from dpcorr.obs.provenance import (  # noqa: F401
    DIVERGENCE_KINDS,
    Provenance,
    build_provenance,
    discover_federation,
)
from dpcorr.obs.recorder import (  # noqa: F401
    FlightRecorder,
    read_dump,
    reconstruct,
)
from dpcorr.obs.slo import (  # noqa: F401
    Alert,
    BurnRateEngine,
    Objective,
    federation_eps_burn_objectives,
    federation_round_latency_objective,
    http_trigger_hook,
    recorder_trigger_hook,
)
from dpcorr.obs.trace import (  # noqa: F401
    Span,
    SpanContext,
    Tracer,
    configure,
    current_span,
    from_wire_headers,
    read_spans,
    to_chrome_trace,
    tracer,
    wire_headers,
    write_chrome_trace,
)
from dpcorr.obs.trajectory import (  # noqa: F401
    Point,
    Regression,
    build_report,
    find_regressions,
    gate_attribution,
)

