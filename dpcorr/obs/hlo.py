"""Compile-time HLO/cost introspection (ISSUE 15).

``utils/compile.py`` is the repo's single compile choke point — every
AOT lowering in sim, grid, serve and protocol flows through
``aot_compile``.  This module rides that choke point: for each compiled
signature it captures XLA's ``cost_analysis()`` (FLOPs, bytes accessed),
the memory analysis, and a fingerprint + op histogram of the optimized
HLO text.  The store is bounded and process-local; ``dump()`` persists
it so two dumps (say, CPU vs TPU, or before/after a reshard fix) can be
compared with the jax-free half of this module —
``dpcorr obs hlo diff`` explains *what changed* between two compiles:
fingerprint flips, FLOP/byte deltas, and op-count deltas (fusion /
copy / transpose / reshape counts are how layout and reshard boundaries
show up in optimized HLO).

Import rule: this module must import WITHOUT jax.  All jax interaction
happens through the ``compiled`` objects handed to the capture
functions; the diff half touches nothing but JSON.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import re
import threading
from typing import Any, Dict, List, Optional

_STORE_CAP = 256

# Matches the op name in an HLO instruction line:
#   %fusion.3 = f32[128]{0} fusion(%p0), kind=kLoop ...
_OP_RE = re.compile(r"=\s*(?:[a-z0-9_\[\]{},:#\s]*?\s)?([a-z][a-z0-9\-]*)\(")


def cost_summary(compiled: Any) -> Dict[str, float]:
    """FLOPs / bytes-accessed from ``compiled.cost_analysis()``.

    Tolerates every spelling jax has shipped: a dict, a list/tuple of
    dicts, ``"bytes accessed"`` vs ``"bytes_accessed"``.  Returns an
    empty dict when the backend offers no analysis.
    """
    try:
        cost = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend-dependent, best effort
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out: Dict[str, float] = {}
    flops = cost.get("flops")
    if isinstance(flops, (int, float)) and flops >= 0:
        out["flops"] = float(flops)
    for key in ("bytes accessed", "bytes_accessed"):
        val = cost.get(key)
        if isinstance(val, (int, float)) and val >= 0:
            out["bytes"] = float(val)
            break
    return out


def memory_summary(compiled: Any) -> Dict[str, int]:
    """Per-signature memory analysis, best effort."""
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return {}
    if mem is None:
        return {}
    out: Dict[str, int] = {}
    for attr in (
        "temp_size_in_bytes",
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        val = getattr(mem, attr, None)
        if isinstance(val, int) and val >= 0:
            out[attr] = val
    return out


def hlo_text(compiled: Any) -> str:
    """Optimized-HLO text of a compiled executable, or ''."""
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001
        return ""
    return text if isinstance(text, str) else ""


def fingerprint(text: str) -> str:
    """Short stable digest of HLO text (16 hex chars)."""
    if not text:
        return ""
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:16]


def op_histogram(text: str) -> Dict[str, int]:
    """Count HLO ops per instruction line.

    fusion/copy/transpose/reshape/convert/all-reduce counts are the
    signal: a copy or transpose appearing between two dumps is a layout
    or reshard boundary XLA inserted.
    """
    hist: collections.Counter = collections.Counter()
    for line in text.splitlines():
        if " = " not in line:
            continue
        m = _OP_RE.search(line)
        if m:
            hist[m.group(1)] += 1
    return dict(hist)


class HloStore:
    """Bounded per-process store of compile records keyed by signature."""

    def __init__(self, cap: int = _STORE_CAP) -> None:
        self._cap = cap
        self._lock = threading.Lock()
        self._recs: "collections.OrderedDict[str, Dict[str, Any]]" = (
            collections.OrderedDict()
        )

    @staticmethod
    def _digest(signature: Optional[Dict[str, Any]]) -> str:
        blob = json.dumps(signature or {}, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]

    def record(
        self,
        signature: Optional[Dict[str, Any]],
        compiled: Any,
        *,
        seconds: float = 0.0,
        cause: str = "",
    ) -> Dict[str, Any]:
        """Capture one compile's analyses into the store."""
        text = hlo_text(compiled)
        rec = {
            "signature": dict(signature or {}),
            "fingerprint": fingerprint(text),
            "cost": cost_summary(compiled),
            "memory": memory_summary(compiled),
            "ops": op_histogram(text),
            "compile_seconds": float(seconds),
            "cause": cause,
        }
        key = self._digest(signature)
        with self._lock:
            self._recs[key] = rec
            self._recs.move_to_end(key)
            while len(self._recs) > self._cap:
                self._recs.popitem(last=False)
        return rec

    def records(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._recs.items()}

    def __len__(self) -> int:
        with self._lock:
            return len(self._recs)

    def dump(self, path: str) -> str:
        """Persist the store as a signature dump for later diffing."""
        payload = {"kind": "dpcorr_hlo_dump", "signatures": self.records()}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path


_default_store: Optional[HloStore] = None
_default_lock = threading.Lock()


def default_store() -> HloStore:
    global _default_store
    with _default_lock:
        if _default_store is None:
            _default_store = HloStore()
        return _default_store


# ---------------------------------------------------------------------------
# jax-free half: load and diff persisted dumps


def load_dump(path: str) -> Dict[str, Dict[str, Any]]:
    """Read a persisted signature dump; raises ValueError on bad shape."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("kind") != "dpcorr_hlo_dump":
        raise ValueError(f"{path}: not a dpcorr_hlo_dump artifact")
    sigs = data.get("signatures")
    if not isinstance(sigs, dict):
        raise ValueError(f"{path}: missing signatures table")
    return sigs


def _sig_label(rec: Dict[str, Any]) -> str:
    sig = rec.get("signature") or {}
    if not sig:
        return "<unsigned>"
    return ",".join(f"{k}={sig[k]}" for k in sorted(sig))


def diff_dumps(
    a: Dict[str, Dict[str, Any]], b: Dict[str, Dict[str, Any]]
) -> Dict[str, Any]:
    """Structural diff of two signature dumps (a = old, b = new)."""
    added = sorted(set(b) - set(a))
    removed = sorted(set(a) - set(b))
    changed: List[Dict[str, Any]] = []
    for key in sorted(set(a) & set(b)):
        ra, rb = a[key], b[key]
        entry: Dict[str, Any] = {
            "signature": rb.get("signature") or ra.get("signature") or {},
            "label": _sig_label(rb),
        }
        delta = False
        if ra.get("fingerprint") != rb.get("fingerprint"):
            entry["fingerprint"] = {
                "old": ra.get("fingerprint"),
                "new": rb.get("fingerprint"),
            }
            delta = True
        cost_d: Dict[str, Dict[str, float]] = {}
        ca, cb = ra.get("cost") or {}, rb.get("cost") or {}
        for field in sorted(set(ca) | set(cb)):
            va, vb = float(ca.get(field, 0.0)), float(cb.get(field, 0.0))
            if va != vb:
                cost_d[field] = {"old": va, "new": vb}
        if cost_d:
            entry["cost"] = cost_d
            delta = True
        mem_d: Dict[str, Dict[str, int]] = {}
        ma, mb = ra.get("memory") or {}, rb.get("memory") or {}
        for field in sorted(set(ma) | set(mb)):
            va, vb = int(ma.get(field, 0)), int(mb.get(field, 0))
            if va != vb:
                mem_d[field] = {"old": va, "new": vb}
        if mem_d:
            entry["memory"] = mem_d
            delta = True
        ops_d: Dict[str, Dict[str, int]] = {}
        oa, ob = ra.get("ops") or {}, rb.get("ops") or {}
        for op in sorted(set(oa) | set(ob)):
            va, vb = int(oa.get(op, 0)), int(ob.get(op, 0))
            if va != vb:
                ops_d[op] = {"old": va, "new": vb}
        if ops_d:
            entry["ops"] = ops_d
            delta = True
        if delta:
            changed.append(entry)
    return {
        "added": [
            {"label": _sig_label(b[k]), "signature": b[k].get("signature", {})}
            for k in added
        ],
        "removed": [
            {"label": _sig_label(a[k]), "signature": a[k].get("signature", {})}
            for k in removed
        ],
        "changed": changed,
    }


def _fmt_num(v: float) -> str:
    for unit, scale in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{unit}"
    return f"{v:g}"


def render_diff(diff: Dict[str, Any]) -> str:
    lines: List[str] = []
    for rec in diff.get("added", []):
        lines.append(f"+ {rec['label']}")
    for rec in diff.get("removed", []):
        lines.append(f"- {rec['label']}")
    for rec in diff.get("changed", []):
        lines.append(f"~ {rec['label']}")
        fp = rec.get("fingerprint")
        if fp:
            lines.append(f"    hlo fingerprint {fp['old']} -> {fp['new']}")
        for field, dd in (rec.get("cost") or {}).items():
            lines.append(
                f"    {field}: {_fmt_num(dd['old'])} -> {_fmt_num(dd['new'])}"
            )
        for field, dd in (rec.get("memory") or {}).items():
            lines.append(
                f"    {field}: {_fmt_num(dd['old'])} -> {_fmt_num(dd['new'])}"
            )
        ops = rec.get("ops") or {}
        if ops:
            parts = [
                f"{op} {dd['old']}->{dd['new']}" for op, dd in sorted(ops.items())
            ]
            lines.append("    ops: " + ", ".join(parts))
    if not lines:
        lines.append("dumps are identical.")
    return "\n".join(lines) + "\n"
