"""Mini observability endpoint for non-serve processes (ISSUE 13).

The fleet telemetry plane (:mod:`dpcorr.obs.fleet`) was built against
serve instances — processes that already carry an HTTP front end with
``/stats`` + ``/metrics`` + ``POST /obs/trigger``. Federation party
processes (``dpcorr federation party``) have no front end at all:
their one job is the pair-link protocol. This module gives any such
process the *scrape surface only*: a tiny threaded HTTP server bound
to ``--obs-port`` serving exactly the three routes FleetCollector and
the SLO engine's page hook speak, off whatever metrics registry and
stats callable the host process hands it. Fully jax-free, zero
dependence on the serve layer.

Routes (byte-compatible with serve's, so every fleet tool — collector,
``obs top``, ``obs fleet``, burn-rate paging — works unchanged):

- ``GET /metrics`` — Prometheus text exposition of the registry.
- ``GET /stats``  — the host's JSON snapshot (``stats_fn()``).
- ``GET /healthz`` — liveness.
- ``POST /obs/trigger`` — validate the reason against the recorder's
  append-only registry and dump THIS process's flight recorder.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dpcorr.obs import recorder as obs_recorder
from dpcorr.obs.metrics import CONTENT_TYPE, Registry


def make_obs_server(registry: Registry, stats_fn=None,
                    host: str = "127.0.0.1", port: int = 0):
    """Build (not start) the endpoint; returns the
    ``ThreadingHTTPServer`` (``.server_address[1]`` is the bound port —
    pass ``port=0`` for an ephemeral one)."""

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, payload: dict) -> None:
            blob = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def _send_text(self, code: int, text: str,
                       content_type: str) -> None:
            blob = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def do_GET(self):  # noqa: N802 (stdlib handler casing)
            if self.path == "/stats":
                try:
                    doc = dict(stats_fn()) if stats_fn is not None else {}
                except Exception as e:
                    self._send(500, {"error":
                                     f"{type(e).__name__}: {e}"})
                    return
                self._send(200, doc)
            elif self.path == "/metrics":
                self._send_text(200, registry.render(), CONTENT_TYPE)
            elif self.path == "/healthz":
                self._send(200, {"ok": True})
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802
            if self.path != "/obs/trigger":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length))
                reason = body.get("reason")
                detail = body.get("detail") or {}
                if reason not in obs_recorder.TRIGGER_REASONS:
                    raise ValueError(
                        f"unknown trigger reason {reason!r}")
                if not isinstance(detail, dict):
                    raise ValueError("detail must be an object")
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": str(e)})
                return
            path = obs_recorder.trigger(
                reason, **{str(k): v for k, v in detail.items()})
            self._send(200, {"dumped": path,
                             "armed": obs_recorder.active()
                             is not None})

        def log_message(self, *args):  # quiet by default
            pass

    return ThreadingHTTPServer((host, port), Handler)


def start_obs_server(registry: Registry, stats_fn=None,
                     host: str = "127.0.0.1", port: int = 0):
    """Start the endpoint on a daemon thread; returns
    ``(server, bound_port)``. The caller announces the port (the party
    banner) and calls ``server.shutdown()`` on exit — or doesn't: the
    daemon thread dies with the process, which is the right lifetime
    for a scrape surface."""
    server = make_obs_server(registry, stats_fn, host=host, port=port)
    thread = threading.Thread(target=server.serve_forever,
                              name="obs-endpoint", daemon=True)
    thread.start()
    return server, server.server_address[1]
