"""Jax-free read half of the sharded budget directory.

serve/budget_dir.py is the write side: sharded per-user ε accounting
with a generation-numbered snapshot + write-ahead journal per shard.
This module is the read side — and the *recovery core* the write side
itself uses — kept in the jax-free obs layer on purpose: the
``dpcorr.serve`` package import pulls the accelerator stack, but the
chaos driver's exact-balance assertions and the ``dpcorr obs budget``
replay must run on an operator laptop with no jax at all. One shared
implementation of the snapshot/WAL arithmetic means the auditor and
the live directory can never drift on what a shard file *means*.

Also home to the durability helpers both the per-party ledger and the
shard files share (satellite of ISSUE 10): the stale-``.tmp`` sweep
and the ``.corrupt`` quarantine — an unparseable durable file is moved
aside whole and refused loudly, never half-applied.
"""

from __future__ import annotations

import json
import os
from typing import Mapping

#: shard snapshot / WAL / meta format version (serve.budget_dir).
DIR_VERSION = 1

#: reserved principal namespaces the composite ledger routes by —
#: party names must never collide with these. scan.ledger_balance
#: filters them when matching wire ε (which is party-leg-only), and
#: :func:`fold_levels` splits a replayed spend table along them.
USER_PREFIX = "user/"
GLOBAL_KEY = "global/total"
RESERVED_PREFIXES = (USER_PREFIX, "global/")


class DirectoryCorruptError(ValueError):
    """A budget-directory shard file could not be parsed. The bad file
    has been quarantined to a ``.corrupt`` sidecar; the message says
    exactly what to do next — never half-applied."""


def sweep_stale_tmp(path: str) -> None:
    """Remove ``{path}.tmp.*`` crash artifacts: a tmp file that was
    never renamed belongs to a write that never committed, and a dead
    writer will never finish it. Shared by the ledger snapshot
    (serve.ledger) and the budget directory's shard files.

    Writers stamp their pid into the suffix (``{path}.tmp.{pid}``), so
    a tmp bearing *our own* pid belongs to a writer in this very
    process — alive by definition, possibly mid-persist on another
    thread (in-proc crash-resume harnesses reopen a journal while the
    pre-crash thread is still draining) — and is skipped."""
    d = os.path.dirname(path) or "."
    prefix = os.path.basename(path) + ".tmp."
    own = str(os.getpid())
    try:
        names = os.listdir(d)
    except OSError:
        return
    for name in names:
        if name.startswith(prefix) and name[len(prefix):] != own:
            try:
                os.unlink(os.path.join(d, name))
            except OSError:
                pass


def quarantine_corrupt(path: str) -> str:
    """Move an unparseable durable file aside to ``{path}.corrupt`` so
    a restart can never half-apply it; returns the sidecar path. The
    caller raises its own loud, actionable error naming the sidecar."""
    quarantined = path + ".corrupt"
    os.replace(path, quarantined)
    return quarantined


def corrupt_error(path: str, why: str) -> DirectoryCorruptError:
    """Quarantine ``path`` and build the loud error to raise."""
    quarantined = quarantine_corrupt(path)
    return DirectoryCorruptError(
        f"budget shard file {path!r} is corrupt ({why}); the bad file "
        f"was moved to {quarantined!r} — nothing was half-applied. To "
        "recover, rebuild per-user balances from the audit trail "
        "(`python -m dpcorr obs budget --audit <trail> --budget-dir "
        "<dir>`) or restore a good snapshot; delete the sidecar only "
        "if losing this shard's spend history is acceptable.")


def fresh_user(now: float) -> dict:
    """Per-user state record. ``s``: current-window spend, ``l``:
    lifetime spend (monotone mod refunds — the audit-replay quantity),
    ``b``: burst credit, ``w``: window start."""
    return {"s": 0.0, "l": 0.0, "b": 0.0, "w": now}


def apply_wal_entry(entry: dict, users: dict,
                    charge_ids: dict, wal_path: str) -> None:
    """Apply one WAL entry to a user table — the single definition of
    what a journal line *means*, shared by live recovery and the
    jax-free reader. Charges dedup on ``charge_id`` exactly like the
    live path (before creating the user, also like the live path);
    refunds clamp at zero and forget the id; renewals carry absolute
    resulting state, so replay is idempotent. ``c``/``r`` entries
    carry the user's window start ``w`` and burst ``b``, consulted
    only when the entry has to *create* the user (state still
    WAL-only, no snapshot line yet): recreating with ``w=0.0`` would
    make the first post-restart charge see billions of elapsed
    periods and fire a spurious renewal that zeroes the window spend,
    letting the user overspend their window budget."""
    kind = entry["k"]
    user = str(entry["u"])
    if kind == "c":
        cid = entry.get("id")
        if cid is not None and cid in charge_ids:
            return
    st = users.get(user)
    if st is None:
        st = users[user] = fresh_user(float(entry.get("w", 0.0)))
        st["b"] = float(entry.get("b", 0.0))
    if kind == "c":
        eps = float(entry["e"])
        st["s"] += eps
        st["l"] += eps
        if cid is not None:
            charge_ids[cid] = None
    elif kind == "r":
        eps = float(entry["e"])
        st["s"] = max(0.0, st["s"] - eps)
        st["l"] = max(0.0, st["l"] - eps)
        cid = entry.get("id")
        if cid is not None:
            charge_ids.pop(cid, None)
    elif kind == "n":
        st["s"] = 0.0
        st["b"] = float(entry["b"])
        st["w"] = float(entry["w"])
    else:
        raise corrupt_error(wal_path, f"unknown entry kind {kind!r}")


def load_shard(base: str) -> dict:
    """Recover one shard's authoritative state from ``{base}.json``
    (snapshot) + ``{base}.wal`` (journal). Returns ``{"gen", "users",
    "charge_ids", "wal_entries", "wal_fresh_needed"}`` —
    ``wal_fresh_needed`` tells the write side the WAL must be
    rewritten (absent, or stale from a crash mid-compaction: its
    generation is behind the snapshot's, so every entry is already
    folded in and replaying would double-apply). Raises
    :class:`DirectoryCorruptError` (after quarantining the bad file)
    on anything unparseable — a torn shard is refused loudly, never
    half-applied."""
    snap_path, wal_path = base + ".json", base + ".wal"
    sweep_stale_tmp(snap_path)
    sweep_stale_tmp(wal_path)
    gen = 0
    users: dict = {}
    charge_ids: dict = {}
    if os.path.exists(snap_path):
        try:
            with open(snap_path, encoding="utf-8") as fh:
                state = json.load(fh)
            if state.get("version") != DIR_VERSION:
                raise ValueError(f"version {state.get('version')!r}")
            gen = int(state["gen"])
            users = {str(u): {"s": float(st["s"]), "l": float(st["l"]),
                              "b": float(st["b"]), "w": float(st["w"])}
                     for u, st in state["users"].items()}
            charge_ids = {str(c): None
                          for c in state.get("charge_ids", [])}
        except (json.JSONDecodeError, UnicodeDecodeError, OSError,
                KeyError, TypeError, ValueError) as e:
            raise corrupt_error(snap_path, str(e)) from e
    entries = _read_wal(wal_path, gen)
    if entries is None:
        return {"gen": gen, "users": users, "charge_ids": charge_ids,
                "wal_entries": 0, "wal_fresh_needed": True}
    for entry in entries:
        apply_wal_entry(entry, users, charge_ids, wal_path)
    return {"gen": gen, "users": users, "charge_ids": charge_ids,
            "wal_entries": len(entries), "wal_fresh_needed": False}


def _read_wal(wal_path: str, snap_gen: int):
    if not os.path.exists(wal_path):
        return None
    try:
        with open(wal_path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except (OSError, UnicodeDecodeError) as e:
        raise corrupt_error(wal_path, str(e)) from e
    if not lines:
        return None
    try:
        header = json.loads(lines[0])
        if header.get("k") != "wal":
            raise ValueError(f"bad header {lines[0]!r}")
        gen = int(header["gen"])
        entries = [json.loads(ln) for ln in lines[1:]]
    except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
        raise corrupt_error(wal_path, str(e)) from e
    if gen < snap_gen:
        # crash between the snapshot rename and the WAL reset
        # (budget.mid_compaction window): discard, never double-apply
        return None
    if gen > snap_gen:
        raise corrupt_error(wal_path,
                            f"generation {gen} is ahead of snapshot "
                            f"generation {snap_gen}")
    return entries


def directory_shards(root: str) -> int:
    """Shard count pinned in the directory's ``meta.json``."""
    meta_path = os.path.join(root, "meta.json")
    try:
        with open(meta_path, encoding="utf-8") as fh:
            return int(json.load(fh)["shards"])
    except (json.JSONDecodeError, UnicodeDecodeError, OSError,
            KeyError, TypeError, ValueError) as e:
        raise corrupt_error(meta_path, str(e)) from e


def read_user_balances(root: str) -> dict[str, dict]:
    """Fold every shard's authoritative state into one ``user →
    {"s", "l", "b", "w"}`` table — read-only, jax-free, no cold-spill
    or live-directory machinery. This is what the chaos driver asserts
    exact per-user balances against, and what ``obs budget
    --budget-dir`` compares the audit-trail replay to."""
    balances: dict[str, dict] = {}
    for i in range(directory_shards(root)):
        shard = load_shard(os.path.join(root, f"shard-{i:04d}"))
        balances.update(shard["users"])
    return balances


def fold_levels(spent: Mapping[str, float]) -> dict[str, dict]:
    """Split a replayed spend table (obs.audit.replay) into the three
    budget levels: ``party`` (data owners), ``user`` (bare user ids,
    ``user/`` prefix stripped), ``global``."""
    out: dict[str, dict] = {"party": {}, "user": {}, "global": {}}
    for principal, eps in spent.items():
        if principal.startswith(USER_PREFIX):
            out["user"][principal[len(USER_PREFIX):]] = eps
        elif principal.startswith("global/"):
            out["global"][principal] = eps
        else:
            out["party"][principal] = eps
    return out
