"""Host↔device transfer accounting for the replication hot path.

The r03→r04 headline halving went unexplained for a round because
nothing recorded *where* the per-block time went — dispatch, reshard,
or fetch. These counters make the donated rep-block pipeline
(``dpcorr.sim.RepBlockPipeline``) and the grid dispatch attributable
from the artifact alone:

- ``dpcorr_transfer_donated_blocks_total`` — blocks dispatched through
  a ``donate_argnums`` kernel (the carry buffers were offered to XLA
  for reuse).
- ``dpcorr_transfer_donation_unused_total`` — dispatches where the
  runtime *declined* a donated buffer (the "Some donated buffers were
  not usable" warning). Zero when donation actually engages — the
  pipeline A/B tests assert on exactly this.
- ``dpcorr_transfer_fetches_total`` — host fetches at a reduction
  boundary (``block_until_ready``/``device_get`` of the accumulator).
  One per pipeline run, not one per block: a rising fetches:blocks
  ratio is the accidental-sync smell the lint ``sync`` rule guards.
- ``dpcorr_transfer_device_put_total`` / ``_bytes_total`` — explicit
  host→device placements (pre-sharding inputs before dispatch).
- ``dpcorr_transfer_reshard_mismatch_total`` — dispatches whose input
  sharding did not match the kernel's declared ``in_shardings`` (XLA
  inserts a copy; on the 1-device CPU box this is free, through the
  TPU tunnel it is the silent tax the explicit shardings exist to
  remove).

All counters live in the process default registry (``dpcorr.obs``), so
``/metrics``, ``benchmarks/roofline.py`` and the bench ``detail`` stamp
read one source of truth.
"""

from __future__ import annotations

import warnings
from typing import Mapping

from dpcorr.obs.metrics import Registry, default_registry

#: substring of the CPython warning emitted when a donated buffer
#: cannot be aliased to any output (jax/_src/interpreters/mlir.py)
_DONATION_WARNING = "donated buffers were not usable"


class TransferCounters:
    """The transfer-counter bundle for one registry (usually the
    process default — construct with an explicit registry in tests so
    concurrent pipelines never cross-contaminate counts)."""

    def __init__(self, registry: Registry | None = None):
        self.registry = registry if registry is not None \
            else default_registry()
        self.donated_blocks = self.registry.counter(
            "dpcorr_transfer_donated_blocks_total",
            "Blocks dispatched through a donate_argnums kernel")
        self.donation_unused = self.registry.counter(
            "dpcorr_transfer_donation_unused_total",
            "Dispatches where the runtime declined a donated buffer")
        self.fetches = self.registry.counter(
            "dpcorr_transfer_fetches_total",
            "Host fetches at a reduction boundary")
        self.device_puts = self.registry.counter(
            "dpcorr_transfer_device_put_total",
            "Explicit host-to-device placements (pre-sharding)")
        self.device_put_bytes = self.registry.counter(
            "dpcorr_transfer_device_put_bytes_total",
            "Bytes moved by explicit host-to-device placements")
        self.reshard_mismatch = self.registry.counter(
            "dpcorr_transfer_reshard_mismatch_total",
            "Dispatches whose input sharding mismatched in_shardings")

    def snapshot(self) -> dict[str, int]:
        """Flat dict for the bench ``detail`` stamp / roofline artifact."""
        return {
            "donated_blocks": int(self.donated_blocks.value()),
            "donation_unused": int(self.donation_unused.value()),
            "fetches": int(self.fetches.value()),
            "device_put": int(self.device_puts.value()),
            "device_put_bytes": int(self.device_put_bytes.value()),
            "reshard_mismatch": int(self.reshard_mismatch.value()),
        }


_default: TransferCounters | None = None


def default_counters() -> TransferCounters:
    """The process-wide bundle over the default registry."""
    global _default
    if _default is None:
        _default = TransferCounters()
    return _default


class donation_watch(warnings.catch_warnings):
    """Context manager that records donation-decline warnings into
    ``counters`` instead of letting them scroll by unattributed. The
    first dispatch of a donated kernel is run under this watch; the
    test satellite's "donation actually engages" assertion is
    ``donation_unused == 0`` plus the pipeline's ``donation_engaged``
    flag this feeds."""

    def __init__(self, counters: TransferCounters):
        super().__init__(record=True)
        self._counters = counters
        self.declined = False

    def __enter__(self):
        self._log = super().__enter__()
        warnings.simplefilter("always")
        return self

    def __exit__(self, *exc):
        for w in self._log:
            if _DONATION_WARNING in str(w.message):
                self.declined = True
                self._counters.donation_unused.inc()
            else:  # re-emit anything we were not looking for
                warnings.warn_explicit(w.message, w.category,
                                       w.filename, w.lineno)
        return super().__exit__(*exc)


def diff(after: Mapping[str, int], before: Mapping[str, int],
         ) -> dict[str, int]:
    """Per-run counter delta between two :meth:`TransferCounters.snapshot`
    calls (counters are process-cumulative; artifacts want the run's own
    contribution)."""
    return {k: int(after[k]) - int(before.get(k, 0)) for k in after}
