"""Per-request cost attribution: where a request's time and ε went.

Dapper-style tracing (obs.trace) answers *when* things happened;
Canopy-style attribution (Kaldor et al., 2017) answers *what one
request cost*. A :class:`CostRecord` rides the serving path next to the
request's root span and accumulates, per request:

- **queue wait** — admission to flush-claim (the coalescer holding it);
- **compile wait** — time the launch spent blocked on a fresh kernel
  compilation (zero on warm-cache requests; serve.kernels reports it);
- **kernel time** — the launch's dispatch-to-fetch interval, divided
  evenly across the riders of one batched launch, so the records of a
  batch sum to the launch's cost instead of multiply-counting it;
- **retries** — client-side attempts beyond the first (stamped by the
  retrying client, serve.client — the server only ever sees attempts);
- **shed / refusal events** — every overload outcome the request hit;
- **ε charged / refunded per party** — the ledger deltas, so a refused
  request provably nets zero (``eps_net``) and a served one nets its
  quoted price.

The record is returned in response metadata (``EstimateResponse.cost``
/ the HTTP body's ``cost`` field), aggregated in ``/stats``, kept in a
bounded :class:`CostRegistry` the flight recorder dumps, and linked to
the latency histogram through :class:`ExemplarStore` — per-bucket trace
exemplars, so an operator can go from a slow histogram bucket straight
to a concrete trace ID and its cost breakdown.

jax-free and import-light: the ``obs`` CLI reconstructs cost records
from flight-recorder dumps without touching the serving stack.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict
from typing import Mapping, Sequence

from dpcorr.obs.metrics import LATENCY_BUCKETS

_local_ids = itertools.count()


def split_exact(total, n: int) -> list:
    """Divide a batched launch's ``total`` (seconds or bytes) across its
    ``n`` riders so the parts sum back to *exactly* the total — the
    add_kernel contract ("divided evenly … so the records of a batch
    sum to the launch's cost") made arithmetic-safe. Integer totals
    split largest-remainder (the first ``total % n`` riders carry one
    extra unit); float totals give every rider the even share and put
    the rounding residual on the last one, so an auditor summing
    per-cell attributions reconciles against the round total without a
    tolerance."""
    if n <= 0:
        raise ValueError(f"cannot split across {n} riders")
    if isinstance(total, int):
        base, extra = divmod(total, n)
        return [base + (1 if i < extra else 0) for i in range(n)]
    share = float(total) / n
    parts = [share] * n
    parts[-1] = float(total) - share * (n - 1)
    return parts


class CostRecord:
    """One request's accumulating cost. Mutated from the admission
    (client) thread and the flush thread, so every update takes the
    record's lock; ``to_dict`` snapshots under the same lock."""

    __slots__ = ("id", "trace_id", "queue_wait_s", "compile_wait_s",
                 "kernel_s", "retries", "events", "eps_charged",
                 "eps_refunded", "_lock")

    def __init__(self, trace_id: str | None = None):
        # untraced servers still attribute cost: fall back to a
        # process-local id so the registry stays keyable
        self.trace_id = trace_id
        self.id = trace_id if trace_id is not None \
            else f"local-{next(_local_ids)}"
        self.queue_wait_s = 0.0  # guarded by: _lock
        self.compile_wait_s = 0.0  # guarded by: _lock
        self.kernel_s = 0.0  # guarded by: _lock
        self.retries = 0  # guarded by: _lock
        self.events: list[str] = []  # guarded by: _lock
        self.eps_charged: dict[str, float] = {}  # guarded by: _lock
        self.eps_refunded: dict[str, float] = {}  # guarded by: _lock
        self._lock = threading.Lock()

    # -- accumulation ----------------------------------------------------
    def charge(self, charges: Mapping[str, float]) -> None:
        with self._lock:
            for p, e in charges.items():
                self.eps_charged[str(p)] = \
                    self.eps_charged.get(str(p), 0.0) + float(e)

    def refund(self, charges: Mapping[str, float],
               reason: str | None = None) -> None:
        with self._lock:
            for p, e in charges.items():
                self.eps_refunded[str(p)] = \
                    self.eps_refunded.get(str(p), 0.0) + float(e)
            if reason is not None:
                self.events.append(f"refund:{reason}")

    def event(self, name: str) -> None:
        """A shed / refusal / degradation the request hit, in order."""
        with self._lock:
            self.events.append(str(name))

    def set_queue_wait(self, seconds: float) -> None:
        with self._lock:
            self.queue_wait_s = float(seconds)

    def add_kernel(self, seconds: float) -> None:
        with self._lock:
            self.kernel_s += float(seconds)

    def add_compile_wait(self, seconds: float) -> None:
        with self._lock:
            self.compile_wait_s += float(seconds)

    def add_retries(self, n: int) -> None:
        with self._lock:
            self.retries += int(n)

    # -- reading ---------------------------------------------------------
    def eps_net(self) -> dict[str, float]:
        """Charged minus refunded per party (clamped at zero, the
        ledger's own refund arithmetic) — zero for every request that
        never launched a kernel."""
        with self._lock:
            parties = set(self.eps_charged) | set(self.eps_refunded)
            return {p: max(0.0, self.eps_charged.get(p, 0.0)
                           - self.eps_refunded.get(p, 0.0))
                    for p in sorted(parties)}

    def to_dict(self) -> dict:
        """The response-metadata / dump form (strict-JSON friendly)."""
        with self._lock:
            net = {p: max(0.0, self.eps_charged.get(p, 0.0)
                          - self.eps_refunded.get(p, 0.0))
                   for p in sorted(set(self.eps_charged)
                                   | set(self.eps_refunded))}
            return {
                "trace_id": self.trace_id,
                "queue_wait_s": round(self.queue_wait_s, 6),
                "compile_wait_s": round(self.compile_wait_s, 6),
                "kernel_s": round(self.kernel_s, 9),
                "retries": self.retries,
                "events": list(self.events),
                "eps_charged": dict(self.eps_charged),
                "eps_refunded": dict(self.eps_refunded),
                "eps_net": net,
            }


class CostRegistry:
    """Bounded LRU map of recent cost records, keyed by record id
    (the trace ID when tracing is on). The server keeps one so refused
    requests — which never produce a response object — still leave an
    inspectable cost trail, and the flight recorder folds the whole
    registry into every dump."""

    def __init__(self, capacity: int = 2048):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._records: OrderedDict[str, CostRecord] = \
            OrderedDict()  # guarded by: _lock

    def new(self, trace_id: str | None = None) -> CostRecord:
        rec = CostRecord(trace_id)
        with self._lock:
            self._records[rec.id] = rec
            self._records.move_to_end(rec.id)
            while len(self._records) > self.capacity:
                self._records.popitem(last=False)
        return rec

    def get(self, rec_id: str) -> CostRecord | None:
        with self._lock:
            return self._records.get(rec_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def records(self) -> list[CostRecord]:
        with self._lock:
            return list(self._records.values())

    def to_dict(self) -> dict[str, dict]:
        """id → cost dict, insertion (≈ admission) order preserved."""
        return {r.id: r.to_dict() for r in self.records()}

    def aggregate(self) -> dict:
        """The ``/stats`` roll-up: totals across the retained window."""
        records = self.records()
        agg = {"records": len(records), "queue_wait_s": 0.0,
               "compile_wait_s": 0.0, "kernel_s": 0.0, "retries": 0,
               "eps_charged": 0.0, "eps_refunded": 0.0}
        for r in records:
            d = r.to_dict()
            agg["queue_wait_s"] += d["queue_wait_s"]
            agg["compile_wait_s"] += d["compile_wait_s"]
            agg["kernel_s"] += d["kernel_s"]
            agg["retries"] += d["retries"]
            agg["eps_charged"] += sum(d["eps_charged"].values())
            agg["eps_refunded"] += sum(d["eps_refunded"].values())
        for k in ("queue_wait_s", "compile_wait_s", "kernel_s",
                  "eps_charged", "eps_refunded"):
            agg[k] = round(agg[k], 9)
        return agg


class ExemplarStore:
    """Latency-histogram trace exemplars: the most recent (value,
    trace_id) landing in each bucket, using the same cumulative-``le``
    bucket bounds as the histogram it annotates. ``/stats`` exposes the
    snapshot and ``/metrics`` renders them as comment lines (exposition
    0.0.4 has no exemplar syntax; comments keep every scraper happy),
    so a slow bucket is one lookup away from a concrete trace."""

    def __init__(self, buckets: Sequence[float] = LATENCY_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._lock = threading.Lock()
        self._by_bucket: dict[str, dict] = {}  # guarded by: _lock

    def _le(self, value: float) -> str:
        for b in self.buckets:
            if value <= b:
                return repr(b)
        return "+Inf"

    def record(self, value: float, trace_id: str | None) -> None:
        if trace_id is None:
            return  # untraced requests have nothing to link to
        le = self._le(float(value))
        with self._lock:
            self._by_bucket[le] = {"trace_id": trace_id,
                                   "value": round(float(value), 6)}

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {le: dict(x) for le, x in self._by_bucket.items()}
