"""Live invariant sentinel: continuous ε-conservation + durability audit.

Every proof surface this repo built so far is *batch*: ``obs budget``
replays a finished trail, ``obs provenance`` merges finished
transcripts, ``protocol scan`` and the fleet conservation gate run
after the fact. This module is the live form — a jax-free daemon
(``dpcorr obs watch``) that **tails the durable artifacts every
subsystem already writes** and re-proves the invariants incrementally,
within a poll of the write:

- serve / stream / party **audit trails** (:mod:`dpcorr.obs.audit`
  JSONL): contiguous ``seq``, the ledger's charge-id idempotency
  (a re-charge must carry ``dedup`` — a bare duplicate spend is
  tampering), and the running per-party ε fold;
- **budget directories** (:mod:`dpcorr.obs.budget_replay` is the
  shared fold core): each user's on-disk lifetime (snapshot + WAL,
  the exact recovery arithmetic) must equal the trail's ``user/``
  legs;
- **stream ingest WAL + release journal** (:mod:`dpcorr.stream.wal`):
  monotone seqs, one release per window, byte-stable release
  artifacts;
- **protocol / federation transcripts + session journals**: a column
  label released as two distinct byte encodings is a correlation
  leak; an artifact charged in two rounds is an ε leak; an
  unparseable session journal breaks resume;
- scraped ``/metrics`` **ledger gauges**: the trail fold and the live
  ``dpcorr_ledger_spent_eps`` series must agree (ε conservation,
  continuously).

State is **bounded**: offsets + prefix digests per tailed file,
FIFO-capped charge-id / label-digest / window-digest tables, one float
per principal for the ε fold. Progress is checkpointed to an fsynced
JSON file after every poll, together with the signatures of violations
already raised — a restarted sentinel resumes at its offsets and never
re-alerts on re-read (the crash-exactness discipline applied to the
auditor itself).

Chaos-clean by construction: the *legal* artifacts of crash recovery
are explicitly not violations — a torn final line is simply never
consumed until its newline lands, a replayed charge arrives
``dedup``-flagged and spends nothing, a journal-skipped (refused)
window was never journaled at all, and the conservation check only
fires after the same mismatch is observed on two consecutive polls (a
scrape racing a charge is not drift). What *does* fire is typed with
:data:`VIOLATION_KINDS` — the provenance vocabulary plus four live
kinds — and each violation names the offending artifact/party, bumps
``dpcorr_sentinel_violations_total``, arms the offender's flight
recorder (``POST /obs/trigger`` reason=``sentinel_violation``) and
pages through the same multi-window burn-rate machinery as every other
SLO (:mod:`dpcorr.obs.slo`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.request

from dpcorr.obs.audit import EVENT_KINDS
from dpcorr.obs.budget_replay import USER_PREFIX, fold_levels
from dpcorr.obs.metrics import Registry, parse_exposition
from dpcorr.obs.provenance import DIVERGENCE_KINDS

__all__ = ["Sentinel", "Violation", "VIOLATION_KINDS",
           "arm_offender_hook"]

#: The full violation vocabulary: every provenance divergence kind the
#: batch auditors speak, plus the four kinds only a live tailer can
#: see. Append-only, like DIVERGENCE_KINDS and TRIGGER_REASONS.
VIOLATION_KINDS = DIVERGENCE_KINDS + (
    "conservation-drift",  # trail fold != ledger gauge / directory fold
    "double-release",      # one window journaled twice, identical bytes
    "wal-regression",      # consumed bytes rewritten/shrunk, or a
                           # monotone seq went backwards
    "checkpoint-gap",      # a gap: missing seq or unparseable line
                           # mid-file (not a torn tail)
)

#: Idempotency memory caps — the sentinel's tables are FIFO-bounded so
#: an unbounded event log cannot grow the verifier (the ledger's own
#: _CHARGE_ID_CAP discipline, sized generously above it).
_SEEN_CAP = 65536
_DIGEST_CAP = 8192

_EPS_TOL = 1e-6


@dataclasses.dataclass(frozen=True)
class Violation:
    """One detected invariant break. ``signature`` identifies the
    violation across polls *and* restarts — it is what the checkpoint
    remembers so nothing ever alerts twice."""

    kind: str
    source: str    # watcher name, e.g. "stream1" — the offender
    artifact: str  # offending file / party / principal
    detail: str
    at: float

    def __post_init__(self):
        assert self.kind in VIOLATION_KINDS, self.kind

    @property
    def signature(self) -> str:
        blob = json.dumps([self.kind, self.source, self.artifact,
                           self.detail], sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["signature"] = self.signature
        return d


def _sha256_prefix(path: str, length: int) -> str:
    h = hashlib.sha256()
    remaining = length
    with open(path, "rb") as fh:
        while remaining > 0:
            chunk = fh.read(min(1 << 20, remaining))
            if not chunk:
                break
            remaining -= len(chunk)
            h.update(chunk)
    return h.hexdigest()


class _FifoSet:
    """Insertion-ordered membership with a FIFO cap (dict-keyed, the
    ledger's own idempotency-memory shape). Serializable."""

    def __init__(self, cap: int, items=()):
        self.cap = int(cap)
        self._d: dict[str, None] = {str(k): None for k in items}

    def add(self, key: str) -> None:
        self._d[str(key)] = None
        while len(self._d) > self.cap:
            self._d.pop(next(iter(self._d)))

    def discard(self, key: str) -> None:
        self._d.pop(str(key), None)

    def __contains__(self, key: str) -> bool:
        return str(key) in self._d

    def to_list(self) -> list[str]:
        return list(self._d)


class _FifoDict:
    """FIFO-capped str→value table (digest / total memories)."""

    def __init__(self, cap: int, items: dict | None = None):
        self.cap = int(cap)
        self._d: dict[str, object] = dict(items or {})

    def get(self, key: str, default=None):
        return self._d.get(str(key), default)

    def set(self, key: str, value) -> None:
        self._d[str(key)] = value
        while len(self._d) > self.cap:
            self._d.pop(next(iter(self._d)))

    def __contains__(self, key: str) -> bool:
        return str(key) in self._d

    def items(self):
        return self._d.items()

    def to_dict(self) -> dict:
        return dict(self._d)


class _Tail:
    """Incremental tailer over one append-only JSONL file with the
    repo's durability grammar baked in:

    - bytes up to ``offset`` were consumed; their sha256 is pinned, so
      any in-place rewrite or truncation of consumed history is a
      ``wal-regression`` (the one thing an append-only store can never
      legally do);
    - a final line without a trailing newline is a *torn tail* — the
      legal residue of a crash mid-append — and simply stays pending
      until its newline lands (or forever: an unacked write is not
      data);
    - a complete line that fails to parse is mid-file corruption —
      ``checkpoint-gap`` — exactly the case the stores themselves
      quarantine on recovery.

    ``on_record(record, line_bytes, emit)`` runs the store-specific
    checks per consumed line.
    """

    def __init__(self, source: str, path: str):
        self.source = source
        self.path = path
        self.offset = 0
        self.digest = hashlib.sha256(b"").hexdigest()
        self.poisoned = False  # structural break found; stop consuming

    # -- checkpoint plumbing ------------------------------------------
    def state(self) -> dict:
        return {"offset": self.offset, "digest": self.digest,
                "poisoned": self.poisoned}

    def restore(self, st: dict) -> None:
        self.offset = int(st.get("offset", 0))
        self.digest = str(st.get("digest", self.digest))
        self.poisoned = bool(st.get("poisoned", False))

    # -- one poll ------------------------------------------------------
    def poll(self, emit, on_record, at: float) -> int:
        """Consume every newly completed line; returns bytes consumed.
        ``emit(kind, artifact, detail)`` raises the violation."""
        if self.poisoned:
            return 0
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = 0
        if size < self.offset:
            self.poisoned = True
            emit("wal-regression", self.path,
                 f"file shrank to {size} bytes below the consumed "
                 f"offset {self.offset} — durable history was "
                 f"truncated or rewound")
            return 0
        if self.offset and _sha256_prefix(self.path,
                                          self.offset) != self.digest:
            self.poisoned = True
            emit("wal-regression", self.path,
                 f"consumed prefix ({self.offset} bytes) no longer "
                 f"matches its recorded sha256 — append-only history "
                 f"was rewritten in place")
            return 0
        if size == self.offset:
            return 0
        with open(self.path, "rb") as fh:
            fh.seek(self.offset)
            blob = fh.read(size - self.offset)
        # only consume through the last newline: the remainder is a
        # (possibly torn) tail still being written
        cut = blob.rfind(b"\n")
        if cut < 0:
            return 0
        consumed = blob[:cut + 1]
        for i, raw in enumerate(consumed.split(b"\n")[:-1]):
            line = raw.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                self.poisoned = True
                emit("checkpoint-gap", self.path,
                     f"unparseable line mid-file at byte "
                     f"{self.offset} (+{i} lines): {e} — not a torn "
                     f"tail; the store itself would quarantine this")
                return 0
            on_record(rec, line, emit)
        self.offset += len(consumed)
        self.digest = _sha256_prefix(self.path, self.offset)
        return len(consumed)


class _AuditWatcher:
    """Incremental :func:`dpcorr.obs.audit.replay` with the live-only
    checks batch replay cannot ask: contiguous seq, and the rule that
    a duplicate spend of a remembered charge id must be
    ``dedup``-flagged (the ledger always flags its replays — a bare
    duplicate line is an injected double charge)."""

    def __init__(self, source: str, path: str):
        self.source = source
        self.tail = _Tail(source, path)
        self.last_seq: int | None = None
        self.spent: dict[str, float] = {}
        self.applied = _FifoSet(_SEEN_CAP)
        #: charge_id → total ε it charged (stream cross-check memory)
        self.charge_totals = _FifoDict(_DIGEST_CAP)

    def state(self) -> dict:
        return {"tail": self.tail.state(), "last_seq": self.last_seq,
                "spent": dict(self.spent),
                "applied": self.applied.to_list(),
                "charge_totals": self.charge_totals.to_dict()}

    def restore(self, st: dict) -> None:
        self.tail.restore(st.get("tail", {}))
        self.last_seq = st.get("last_seq")
        self.spent = {str(k): float(v)
                      for k, v in st.get("spent", {}).items()}
        self.applied = _FifoSet(_SEEN_CAP, st.get("applied", ()))
        self.charge_totals = _FifoDict(
            _DIGEST_CAP, st.get("charge_totals", {}))

    def levels(self) -> dict[str, dict]:
        return fold_levels(self.spent)

    def poll(self, emit, at: float) -> int:
        return self.tail.poll(emit, self._event, at)

    def _event(self, ev: dict, raw: bytes, emit) -> None:
        if not isinstance(ev, dict) or ev.get("kind") not in EVENT_KINDS:
            emit("checkpoint-gap", self.tail.path,
                 f"line is not an audit event: {ev!r:.120}")
            return
        seq = int(ev.get("seq", -1))
        if self.last_seq is not None:
            if seq <= self.last_seq:
                emit("wal-regression", self.tail.path,
                     f"audit seq went backwards: {seq} after "
                     f"{self.last_seq} (a duplicated or replayed line)")
            elif seq != self.last_seq + 1:
                emit("checkpoint-gap", self.tail.path,
                     f"audit seq gap: {seq} after {self.last_seq} — "
                     f"events were dropped from the trail")
        self.last_seq = max(seq, self.last_seq or seq)
        kind, cid = ev["kind"], ev.get("charge_id")
        # the ledger's idempotency arithmetic, incrementally
        # (mirrors audit._dedup_walk / replay exactly)
        if kind == "charge" and cid is not None:
            if cid in self.applied:
                if not ev.get("dedup"):
                    emit("double-charged-artifact", self.tail.path,
                         f"charge id {cid!r} spent twice without the "
                         f"ledger's dedup flag — an injected double "
                         f"charge, not a crash replay")
                return
            self.applied.add(cid)
        elif kind == "refund" and cid is not None:
            self.applied.discard(cid)
        if kind == "charge":
            total = 0.0
            for p, e in ev.get("charges", {}).items():
                self.spent[p] = self.spent.get(p, 0.0) + float(e)
                # the per-charge total is *party* ε — the derived
                # user/global legs mirror it, they don't add to it
                if not (p.startswith(USER_PREFIX)
                        or p.startswith("global/")):
                    total += float(e)
            if cid is not None:
                self.charge_totals.set(cid, total)
        elif kind == "refund":
            for p, e in ev.get("charges", {}).items():
                self.spent[p] = max(0.0,
                                    self.spent.get(p, 0.0) - float(e))


class _StreamWatcher:
    """Ingest-WAL + release-journal invariants for one stream workdir:
    monotone contiguous seqs on both logs, one journal entry per
    window (byte-stable: an identical re-append is ``double-release``,
    a perturbed one is ``re-noised-artifact``), and every journaled
    window's idempotent charge id present exactly once in the
    workdir's own audit trail with the entry's ``eps_window``."""

    def __init__(self, source: str, workdir: str):
        self.source = source
        self.workdir = workdir
        self.wal = _Tail(source, os.path.join(workdir, "wal.jsonl"))
        self.journal = _Tail(source,
                             os.path.join(workdir, "releases.jsonl"))
        self.audit = _AuditWatcher(source,
                                   os.path.join(workdir, "audit.jsonl"))
        self.wal_seq: int | None = None
        self.release_seq: int | None = None
        #: window_id → sha256 of the entry minus release_seq
        self.window_digests = _FifoDict(_DIGEST_CAP)
        #: journaled charges awaiting their audit line (one-poll grace:
        #: the journal append trails the charge, never leads it)
        self.pending_charges: dict[str, float] = {}

    def state(self) -> dict:
        return {"wal": self.wal.state(), "journal": self.journal.state(),
                "audit": self.audit.state(), "wal_seq": self.wal_seq,
                "release_seq": self.release_seq,
                "window_digests": self.window_digests.to_dict(),
                "pending_charges": dict(self.pending_charges)}

    def restore(self, st: dict) -> None:
        self.wal.restore(st.get("wal", {}))
        self.journal.restore(st.get("journal", {}))
        self.audit.restore(st.get("audit", {}))
        self.wal_seq = st.get("wal_seq")
        self.release_seq = st.get("release_seq")
        self.window_digests = _FifoDict(
            _DIGEST_CAP, st.get("window_digests", {}))
        self.pending_charges = {
            str(k): float(v)
            for k, v in st.get("pending_charges", {}).items()}

    def poll(self, emit, at: float) -> int:
        n = self.audit.poll(emit, at)
        # charges journaled on a *previous* poll must have their audit
        # line by now (the service charges before it journals) —
        # checked before this round's journal poll so a charge whose
        # trail append raced our last audit read gets one full round
        for cid, want in list(self.pending_charges.items()):
            got = self.audit.charge_totals.get(cid)
            if got is None:
                emit("tampered-charge", self.journal.path,
                     f"journaled window charge {cid!r} never appeared "
                     f"in the audit trail — a release without its ε")
            elif abs(float(got) - want) > _EPS_TOL:
                emit("eps-total-mismatch", self.journal.path,
                     f"charge {cid!r}: journal says eps_window={want}, "
                     f"audit trail charged {got}")
            del self.pending_charges[cid]
        n += self.wal.poll(emit, self._wal_record, at)
        n += self.journal.poll(emit, self._journal_record, at)
        return n

    def _wal_record(self, rec: dict, raw: bytes, emit) -> None:
        seq = int(rec.get("seq", 0))
        if self.wal_seq is not None:
            if seq <= self.wal_seq:
                emit("wal-regression", self.wal.path,
                     f"ingest WAL seq went backwards: {seq} after "
                     f"{self.wal_seq}")
            elif seq != self.wal_seq + 1:
                emit("checkpoint-gap", self.wal.path,
                     f"ingest WAL seq gap: {seq} after {self.wal_seq} "
                     f"— acked batches were dropped")
        self.wal_seq = max(seq, self.wal_seq or seq)

    def _journal_record(self, rec: dict, raw: bytes, emit) -> None:
        wid = str(rec.get("window_id"))
        body = {k: v for k, v in rec.items() if k != "release_seq"}
        digest = hashlib.sha256(
            json.dumps(body, sort_keys=True).encode()).hexdigest()
        prior = self.window_digests.get(wid)
        if prior is not None:
            if prior == digest:
                emit("double-release", self.journal.path,
                     f"window {wid} journaled twice with identical "
                     f"bytes — one release served as two")
            else:
                emit("re-noised-artifact", self.journal.path,
                     f"window {wid} re-journaled with different bytes "
                     f"— a re-noised substitute of a released "
                     f"artifact (noise averaging leak)")
            return
        self.window_digests.set(wid, digest)
        seq = int(rec.get("release_seq", 0))
        if self.release_seq is not None:
            if seq <= self.release_seq:
                emit("wal-regression", self.journal.path,
                     f"release_seq went backwards: {seq} after "
                     f"{self.release_seq} (window {wid})")
                # a known-tampered entry spawns no derived checks —
                # one injected line is one alert, not a cascade
                return
            if seq != self.release_seq + 1:
                emit("checkpoint-gap", self.journal.path,
                     f"release_seq gap: {seq} after {self.release_seq} "
                     f"(window {wid}) — a release vanished")
        self.release_seq = max(seq, self.release_seq or seq)
        cid = rec.get("charge_id")
        if cid is not None:
            got = self.audit.charge_totals.get(cid)
            want = float(rec.get("eps_window", 0.0))
            if got is None:
                # audit line may land this same poll round; grace it
                self.pending_charges[str(cid)] = want
            elif abs(float(got) - want) > _EPS_TOL:
                emit("eps-total-mismatch", self.journal.path,
                     f"charge {cid!r}: journal says eps_window={want}, "
                     f"audit trail charged {got}")


class _TranscriptWatcher:
    """Incremental form of the cross-pair correlation-leak gate
    (:func:`dpcorr.protocol.scan.scan_federation`): per released
    column label, the canonical encoding's sha256 must be identical in
    every session that carries it, and each artifact may be charged in
    exactly one (session, round) venue."""

    def __init__(self, source: str, directory: str):
        self.source = source
        self.directory = directory
        self.tails: dict[str, _Tail] = {}
        self.label_digests = _FifoDict(_DIGEST_CAP)
        self.charge_venues = _FifoDict(_DIGEST_CAP)

    def state(self) -> dict:
        return {"tails": {p: t.state() for p, t in self.tails.items()},
                "label_digests": self.label_digests.to_dict(),
                "charge_venues": self.charge_venues.to_dict()}

    def restore(self, st: dict) -> None:
        for p, ts in st.get("tails", {}).items():
            t = _Tail(self.source, p)
            t.restore(ts)
            self.tails[p] = t
        self.label_digests = _FifoDict(
            _DIGEST_CAP, st.get("label_digests", {}))
        self.charge_venues = _FifoDict(
            _DIGEST_CAP, st.get("charge_venues", {}))

    def _discover(self) -> None:
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(self.directory, name)
            if path not in self.tails:
                self.tails[path] = _Tail(self.source, path)

    def poll(self, emit, at: float) -> int:
        self._discover()
        return sum(t.poll(emit, self._entry, at)
                   for t in sorted(self.tails.values(),
                                   key=lambda t: t.path))

    def _entry(self, entry: dict, raw: bytes, emit) -> None:
        from dpcorr.protocol.messages import canonical_encode

        w = entry.get("wire") if isinstance(entry, dict) else None
        if not isinstance(w, dict):
            return
        sess = w.get("session", "?")
        payload = w.get("payload") or {}
        mtype = w.get("msg_type")
        if mtype == "release" and isinstance(payload.get("artifacts"),
                                             dict):
            for lab, group in payload["artifacts"].items():
                enc = (canonical_encode(group) if isinstance(group, dict)
                       else repr(group).encode())
                digest = hashlib.sha256(enc).hexdigest()
                prior = self.label_digests.get(lab)
                if prior is not None and prior != digest:
                    emit("re-noised-artifact", str(lab),
                         f"column {lab!r} released as different bytes "
                         f"in session {sess!r} than previously seen — "
                         f"re-noised releases of one column are "
                         f"subtractable")
                elif prior is None:
                    self.label_digests.set(lab, digest)
        if mtype in ("release", "result"):
            side = "x" if mtype == "release" else "y"
            for lab in payload.get("charged", ()) or ():
                key = f"{side}:{lab}"
                venue = [str(sess), str(payload.get("round"))]
                prior = self.charge_venues.get(key)
                if prior is not None and list(prior) != venue:
                    emit("double-charged-artifact", str(lab),
                         f"artifact ({side}, {lab!r}) charged in "
                         f"{prior} and again in {venue} — the plan "
                         f"charges each artifact exactly once")
                elif prior is None:
                    self.charge_venues.set(key, venue)


class _JournalFileWatcher:
    """Session-journal durability: every ``journal.*.json`` snapshot
    in the directory must stay a parseable JSON object (tmp + fsync +
    rename writes can leave no other legal state — an unparseable
    journal is tampering, and it breaks crash resume)."""

    def __init__(self, source: str, directory: str):
        self.source = source
        self.directory = directory

    def state(self) -> dict:
        return {}

    def restore(self, st: dict) -> None:
        pass

    def poll(self, emit, at: float) -> int:
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return 0
        for name in names:
            if not (name.startswith("journal.")
                    and name.endswith(".json")):
                continue
            path = os.path.join(self.directory, name)
            try:
                with open(path, encoding="utf-8") as fh:
                    doc = json.load(fh)
                if not isinstance(doc, dict):
                    raise ValueError("not an object")
            except (OSError, ValueError) as e:
                emit("checkpoint-gap", path,
                     f"session journal unreadable: {e} — resume from "
                     f"this journal is broken")
        return 0


class _ConservationCheck:
    """ε-conservation between an audit watcher's running fold and a
    live reference — the scraped ``dpcorr_ledger_spent_eps`` gauges
    and/or a budget directory's on-disk user balances. Debounced: the
    same mismatch must hold on two consecutive polls (a scrape racing
    a charge, or a trail line landing a poll behind its gauge, is not
    drift — drift is a disagreement that *persists* at quiescence)."""

    def __init__(self, source: str, audit: _AuditWatcher,
                 url: str | None = None,
                 budget_dir: str | None = None,
                 timeout_s: float = 5.0):
        self.source = source
        self.audit = audit
        self.url = url.rstrip("/") if url else None
        self.budget_dir = budget_dir
        self.timeout_s = timeout_s
        self._last_mismatch: dict[str, tuple] = {}

    def state(self) -> dict:
        return {}

    def restore(self, st: dict) -> None:
        pass

    def _debounced(self, key: str, pair: tuple, emit, artifact: str,
                   detail: str) -> None:
        if self._last_mismatch.get(key) == pair:
            emit("conservation-drift", artifact, detail)
            del self._last_mismatch[key]
        else:
            self._last_mismatch[key] = pair

    def poll(self, emit, at: float) -> int:
        levels = self.audit.levels()
        seen: set[str] = set()
        if self.url is not None:
            try:
                with urllib.request.urlopen(
                        f"{self.url}/metrics",
                        timeout=self.timeout_s) as resp:
                    series = parse_exposition(
                        resp.read().decode("utf-8"))
            except (urllib.error.URLError, OSError, ValueError):
                series = None  # a down instance is not ε drift
            if series is not None:
                gauges = {}
                for key, value in series.items():
                    if key.startswith('dpcorr_ledger_spent_eps{party="'):
                        party = key.split('party="', 1)[1].rsplit('"', 1)[0]
                        gauges[party] = value
                fold = dict(levels.get("party", {}))
                fold.update(levels.get("global", {}))
                for party in sorted(set(gauges) | set(fold)):
                    want, got = fold.get(party, 0.0), gauges.get(party,
                                                                 0.0)
                    key = f"gauge:{party}"
                    seen.add(key)
                    if abs(want - got) > _EPS_TOL:
                        self._debounced(
                            key, (round(want, 9), round(got, 9)), emit,
                            party,
                            f"audit-trail fold says {party!r} spent "
                            f"{want:.9g} but the live ledger gauge "
                            f"reads {got:.9g} — ε is not conserved")
        if self.budget_dir is not None and os.path.isdir(self.budget_dir):
            from dpcorr.obs.budget_replay import read_user_balances

            replayed = {p[len(USER_PREFIX):]: s
                        for p, s in self.audit.spent.items()
                        if p.startswith(USER_PREFIX)}
            try:
                balances = read_user_balances(self.budget_dir)
            except ValueError as e:
                emit("checkpoint-gap", self.budget_dir,
                     f"budget directory unreadable: {e}")
                balances = {}
            for user in sorted(set(replayed) | set(balances)):
                want = replayed.get(user, 0.0)
                got = balances.get(user, {}).get("l", 0.0)
                key = f"dir:{user}"
                seen.add(key)
                if abs(want - got) > _EPS_TOL:
                    self._debounced(
                        key, (round(want, 9), round(got, 9)), emit,
                        f"{USER_PREFIX}{user}",
                        f"audit-trail fold says user {user!r} spent "
                        f"{want:.9g} lifetime but the budget "
                        f"directory reconstructs {got:.9g}")
        # a mismatch that healed (values moved) resets its debounce
        for key in list(self._last_mismatch):
            if key not in seen:
                del self._last_mismatch[key]
        return 0


def arm_offender_hook(urls, timeout_s: float = 5.0):
    """Violation hook: POST the violation to the *offending* source's
    ``/obs/trigger`` endpoint with reason ``sentinel_violation`` — the
    flight recorder dumps inside the offender, next to its rings
    (the :func:`dpcorr.obs.slo.http_trigger_hook` shape). Never raises:
    an unreachable offender is already the incident."""
    def hook(violation: Violation) -> None:
        base = urls.get(violation.source)
        if base is None:
            return
        body = json.dumps({"reason": "sentinel_violation",
                           "detail": violation.to_dict()}).encode()
        req = urllib.request.Request(
            f"{base.rstrip('/')}/obs/trigger", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s):
                pass
        except (urllib.error.URLError, OSError):
            pass
    return hook


class Sentinel:
    """The live invariant watchdog: a set of incremental watchers, one
    fsynced checkpoint, one metrics registry, one burn-rate engine.

    Construct, attach sources (:meth:`add_stream`, :meth:`add_audit`,
    :meth:`add_transcripts`, :meth:`add_journals`), then drive
    :meth:`poll` on an interval (or :meth:`run`). Each poll consumes
    newly durable bytes, runs every check, pages on anything new, and
    checkpoints — so a killed sentinel restarted from the same
    checkpoint resumes mid-file and stays silent about everything it
    already raised.
    """

    CHECKPOINT_VERSION = 1

    def __init__(self, checkpoint: str, *,
                 registry: Registry | None = None,
                 instance: str = "sentinel",
                 urls: dict[str, str] | None = None,
                 on_violation=None, on_page=None,
                 clock=time.time, fsync: bool = True,
                 scrape_timeout_s: float = 5.0):
        self.checkpoint_path = checkpoint
        self.instance = instance
        self.urls = dict(urls or {})
        self.clock = clock
        self.fsync = fsync
        self.scrape_timeout_s = scrape_timeout_s
        self.registry = registry if registry is not None else Registry()
        self.on_violation = on_violation
        self._arm = arm_offender_hook(self.urls,
                                      timeout_s=scrape_timeout_s)
        self._watchers: dict[str, object] = {}
        self._alerted = _FifoSet(_SEEN_CAP)
        self.violations: list[Violation] = []  # new this run, in order

        self._info_g = self.registry.gauge(
            "dpcorr_sentinel_instance_info",
            "sentinel identity: constant 1 labelled by instance name",
            labelnames=("instance",))
        self._info_g.set(1, instance=instance)
        self._polls = self.registry.counter(
            "dpcorr_sentinel_polls_total", "Sentinel poll rounds")
        self._checks = self.registry.counter(
            "dpcorr_sentinel_checks_total",
            "Invariant checks performed (watcher-polls)")
        self._violations_c = self.registry.counter(
            "dpcorr_sentinel_violations_total",
            "Invariant violations by kind", labelnames=("kind",))
        self._bytes = self.registry.counter(
            "dpcorr_sentinel_consumed_bytes_total",
            "Durable bytes consumed and verified")
        self._watchers_g = self.registry.gauge(
            "dpcorr_sentinel_watchers", "Attached watchers")
        self._last_poll_g = self.registry.gauge(
            "dpcorr_sentinel_last_poll_ts",
            "Wall timestamp of the last completed poll")

        # violations page through the standard multi-window burn-rate
        # machinery (obs.slo): zero-tolerance error objective over the
        # sentinel's own exposition — any violation is an instant,
        # confirmed burn, and the page arms the flight recorder
        # through the engine's existing hook indirection.
        from dpcorr.obs import slo as _slo

        self._engine = _slo.BurnRateEngine(
            [_slo.Objective(
                name="sentinel-violations", kind="error", target=1e-9,
                total_series=("dpcorr_sentinel_checks_total",),
                bad_series=("dpcorr_sentinel_violations_total",))],
            clock=self.clock,
            on_page=(on_page if on_page is not None
                     else _slo.recorder_trigger_hook(
                         sentinel=instance)))
        self._load_checkpoint()

    # -- wiring --------------------------------------------------------
    def add_stream(self, name: str, workdir: str,
                   url: str | None = None) -> None:
        """Watch one stream workdir (wal/releases/audit + budget_dir
        when present); ``url`` adds the live ledger-gauge conservation
        check and makes the stream armable on violation."""
        w = _StreamWatcher(name, workdir)
        self._watchers[f"{name}/stream"] = w
        bd = os.path.join(workdir, "budget_dir")
        self._watchers[f"{name}/conservation"] = _ConservationCheck(
            name, w.audit, url=url or self.urls.get(name),
            budget_dir=bd if os.path.isdir(bd) else None,
            timeout_s=self.scrape_timeout_s)
        if url is not None:
            self.urls[name] = url

    def add_audit(self, name: str, path: str, url: str | None = None,
                  budget_dir: str | None = None) -> None:
        """Watch one bare audit trail (a serve replica or a protocol
        party); ``url``/``budget_dir`` add the conservation legs."""
        w = _AuditWatcher(name, path)
        self._watchers[f"{name}/audit"] = w
        if url is not None or budget_dir is not None:
            self._watchers[f"{name}/conservation"] = _ConservationCheck(
                name, w, url=url or self.urls.get(name),
                budget_dir=budget_dir, timeout_s=self.scrape_timeout_s)
        if url is not None:
            self.urls[name] = url

    def add_transcripts(self, name: str, directory: str) -> None:
        """Watch a directory of pair-link transcripts for byte-stable
        reuse and exactly-once artifact charging."""
        self._watchers[f"{name}/transcripts"] = _TranscriptWatcher(
            name, directory)

    def add_journals(self, name: str, directory: str) -> None:
        """Watch a directory of session-journal snapshots."""
        self._watchers[f"{name}/journals"] = _JournalFileWatcher(
            name, directory)

    # -- checkpoint ----------------------------------------------------
    def _load_checkpoint(self) -> None:
        try:
            with open(self.checkpoint_path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return
        if doc.get("version") != self.CHECKPOINT_VERSION:
            return
        self._alerted = _FifoSet(_SEEN_CAP, doc.get("alerted", ()))
        self._pending_restore = doc.get("watchers", {})
        for key, st in self._pending_restore.items():
            w = self._watchers.get(key)
            if w is not None:
                w.restore(st)

    def _restore_late(self) -> None:
        """Watchers attached after construction pick up their state on
        the first poll (the CLI builds the sentinel, then wires)."""
        pend = getattr(self, "_pending_restore", None)
        if not pend:
            return
        for key, st in pend.items():
            w = self._watchers.get(key)
            if w is not None:
                w.restore(st)
        self._pending_restore = None

    def save_checkpoint(self) -> None:
        doc = {"version": self.CHECKPOINT_VERSION,
               "instance": self.instance,
               "alerted": self._alerted.to_list(),
               "watchers": {k: w.state()
                            for k, w in self._watchers.items()}}
        d = os.path.dirname(self.checkpoint_path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{self.checkpoint_path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, sort_keys=True)
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, self.checkpoint_path)

    # -- polling -------------------------------------------------------
    def poll(self, at: float | None = None) -> list[Violation]:
        """One verification round over every watcher; returns the NEW
        violations (never anything already alerted — this run or any
        checkpointed previous run)."""
        self._restore_late()
        t = float(at) if at is not None else self.clock()
        new: list[Violation] = []

        def emitter(source: str):
            def emit(kind: str, artifact: str, detail: str) -> None:
                v = Violation(kind=kind, source=source,
                              artifact=str(artifact), detail=detail,
                              at=t)
                if v.signature in self._alerted:
                    return
                self._alerted.add(v.signature)
                new.append(v)
            return emit

        # tails first, conservation second: the cross-checks must see
        # the fold *including* everything this round consumed
        ordered = sorted(self._watchers)
        for pass_cons in (False, True):
            for key in ordered:
                w = self._watchers[key]
                if isinstance(w, _ConservationCheck) != pass_cons:
                    continue
                self._checks.inc()
                self._bytes.inc(w.poll(emitter(w.source), t))
        for v in new:
            self.violations.append(v)
            self._violations_c.inc(kind=v.kind)
            self._arm(v)
            if self.on_violation is not None:
                self.on_violation(v)
        self._polls.inc()
        self._watchers_g.set(float(len(self._watchers)))
        self._last_poll_g.set(t)
        # feed the burn-rate engine off our own exposition — the same
        # series a remote SLO evaluator would scrape
        from dpcorr.obs.fleet import parse_families

        self._engine.observe(
            {self.instance: parse_families(self.registry.render())},
            at=t)
        self._engine.evaluate(at=t)
        self.save_checkpoint()
        return new

    def run(self, interval_s: float = 1.0,
            max_polls: int | None = None,
            stop: threading.Event | None = None) -> int:
        """The daemon loop; returns the CI exit code (1 if this run
        raised any violation)."""
        polls = 0
        while True:
            self.poll()
            polls += 1
            if max_polls is not None and polls >= max_polls:
                break
            if stop is not None and stop.wait(interval_s):
                break
            if stop is None:
                time.sleep(interval_s)
        return self.rc

    @property
    def rc(self) -> int:
        return 1 if self.violations else 0

    def stats(self) -> dict:
        """The ``/stats`` snapshot for the sentinel's own obs
        endpoint (:mod:`dpcorr.obs.endpoint`)."""
        return {
            "kind": "sentinel",
            "instance": self.instance,
            "watchers": sorted(self._watchers),
            "violations": [v.to_dict() for v in self.violations[-64:]],
            "violations_total": len(self.violations),
            "pages": [a.to_dict() for a in self._engine.alerts[-16:]],
            "checkpoint": self.checkpoint_path,
        }
