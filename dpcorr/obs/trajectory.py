"""Bench-trajectory regression engine (ISSUE 15) — jax-free.

The repo accumulates benchmark evidence in three places: the repo-root
``BENCH_r0*.json`` wrappers (a command transcript plus a ``parsed``
payload), the repo-root ``MULTICHIP_r0*.json`` status stamps, and the
direct artifacts under ``benchmarks/results/`` (including
``last_known_good.json``).  PR 6's ``--gate`` can refuse a regression
but only against a single last-known-good value; it cannot say *which*
artifact in the trajectory first bent the curve.  This module is that
answer: it normalizes every artifact it can find into ``Point`` records,
groups them into per-``(device_kind, metric)`` series, walks each series
in round order, and names the first artifact whose value fell below
``floor ×`` the best value seen before it.

Everything here is stdlib-only and must stay importable (and runnable)
without jax — ``dpcorr obs trajectory`` is an operator tool that runs on
laptops with nothing but a checkout.  Malformed artifacts are never
fatal: they become skip notes in the report.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

DEFAULT_FLOOR = 0.85

_ROUND_RE = re.compile(r"r(\d+)")

# Artifact filename globs we consider, relative to each root.
_PATTERNS = ("BENCH_", "MULTICHIP_")


@dataclasses.dataclass
class Point:
    """One normalized benchmark observation."""

    path: str
    round: Optional[int]
    metric: str
    value: float
    unit: str = ""
    device_kind: str = "unknown"
    captured_utc: str = ""
    detail: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "round": self.round,
            "metric": self.metric,
            "value": self.value,
            "unit": self.unit,
            "device_kind": self.device_kind,
            "captured_utc": self.captured_utc,
        }


@dataclasses.dataclass
class Status:
    """A non-numeric artifact (e.g. MULTICHIP probe stamps)."""

    path: str
    round: Optional[int]
    ok: Optional[bool]
    skipped: Optional[bool]
    note: str = ""

    def as_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "round": self.round,
            "ok": self.ok,
            "skipped": self.skipped,
            "note": self.note,
        }


@dataclasses.dataclass
class Regression:
    """First point in a series that fell below floor × best-so-far."""

    series: Tuple[str, str]  # (device_kind, metric)
    path: str
    value: float
    best_value: float
    best_path: str
    ratio: float
    floor: float

    def as_dict(self) -> Dict[str, Any]:
        return {
            "device_kind": self.series[0],
            "metric": self.series[1],
            "path": self.path,
            "value": self.value,
            "best_value": self.best_value,
            "best_path": self.best_path,
            "ratio": self.ratio,
            "floor": self.floor,
        }


def _round_of(name: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(name))
    return int(m.group(1)) if m else None


def derive_device_kind(detail: Dict[str, Any], top: Dict[str, Any]) -> str:
    """Resolve device_kind with fallbacks for pre-ISSUE-11 artifacts.

    Old artifacts only carry a device *string* like ``"TFRT_CPU_0"`` or
    ``"TPU v5 lite0"`` — derive the kind from it so old and new rounds
    land in the same series.

    A multi-device measurement (``detail.device_count`` > 1, stamped by
    bench/mesh-scaling since ISSUE 19) gets a ``x<count>`` suffix —
    ``cpux4`` — so an N-way sharded series is never folded into (or
    regression-walked against) the 1-device series of the same chip.
    Absent or 1 keeps the bare kind: every historical series label is
    unchanged.
    """
    kind = ""
    for src in (detail, top):
        dk = src.get("device_kind")
        if isinstance(dk, str) and dk:
            kind = dk
            break
    if not kind:
        dev = detail.get("device") or top.get("device") or ""
        if isinstance(dev, str) and dev:
            low = dev.lower()
            if "tpu" in low:
                kind = "tpu"
            elif "gpu" in low or "cuda" in low or "rocm" in low:
                kind = "gpu"
            elif "cpu" in low:
                kind = "cpu"
    if not kind:
        return "unknown"
    nd = detail.get("device_count")
    if isinstance(nd, int) and not isinstance(nd, bool) and nd > 1:
        return f"{kind}x{nd}"
    return kind


def _point_from_payload(
    path: str, payload: Dict[str, Any], notes: List[str]
) -> Optional[Point]:
    """Normalize a metric-bearing dict (direct artifact or ``parsed``)."""
    metric = payload.get("metric")
    value = payload.get("value")
    if not isinstance(metric, str) or not metric:
        notes.append(f"{path}: no metric field — skipped")
        return None
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        notes.append(f"{path}: non-numeric value {value!r} — skipped")
        return None
    if value <= 0:
        notes.append(f"{path}: non-positive value {value} — skipped")
        return None
    detail = payload.get("detail")
    detail = detail if isinstance(detail, dict) else {}
    return Point(
        path=path,
        round=_round_of(path),
        metric=metric,
        value=float(value),
        unit=str(payload.get("unit", "") or ""),
        device_kind=derive_device_kind(detail, payload),
        captured_utc=str(payload.get("captured_utc", "") or ""),
        detail=detail,
    )


def load_artifact(
    path: str, notes: List[str], statuses: List[Status]
) -> Optional[Point]:
    """Load one JSON artifact into a Point, Status, or skip note."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError) as exc:
        notes.append(f"{path}: unreadable ({exc.__class__.__name__}) — skipped")
        return None
    if not isinstance(data, dict):
        notes.append(f"{path}: not a JSON object — skipped")
        return None

    # MULTICHIP-style status stamp: no metric, just ok/skipped.
    if "metric" not in data and "parsed" not in data and (
        "ok" in data or "skipped" in data
    ):
        statuses.append(
            Status(
                path=path,
                round=_round_of(path),
                ok=data.get("ok"),
                skipped=data.get("skipped"),
                note=str(data.get("tail", "") or "")[-120:],
            )
        )
        return None

    # BENCH_r* wrapper: the payload lives under "parsed" (may be null
    # when the wrapped command failed — rc is the tell).
    if "parsed" in data:
        parsed = data.get("parsed")
        if not isinstance(parsed, dict):
            rc = data.get("rc")
            notes.append(f"{path}: parsed is null (rc={rc}) — skipped")
            return None
        return _point_from_payload(path, parsed, notes)

    # Direct artifact (benchmarks/results/*, last_known_good.json).
    return _point_from_payload(path, data, notes)


def discover(roots: Sequence[str]) -> List[str]:
    """Find candidate artifact files under the given roots.

    A root that is a file is taken verbatim; a directory contributes
    its ``*.json`` files (non-recursive — ``benchmarks/results`` holds
    trace *directories* we must not descend into) plus repo-root
    ``BENCH_*``/``MULTICHIP_*`` stamps.
    """
    out: List[str] = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        if not os.path.isdir(root):
            continue
        try:
            names = sorted(os.listdir(root))
        except OSError:
            continue
        for name in names:
            full = os.path.join(root, name)
            if not os.path.isfile(full):
                continue
            if not name.endswith(".json"):
                continue
            base = os.path.basename(os.path.normpath(root))
            if base == "results" or any(name.startswith(p) for p in _PATTERNS):
                # results/ dirs contribute every artifact; other roots
                # (the repo root) only their BENCH_/MULTICHIP_ stamps.
                out.append(full)
    # Dedup preserving order.
    seen = set()
    uniq = []
    for p in out:
        rp = os.path.normpath(p)
        if rp not in seen:
            seen.add(rp)
            uniq.append(rp)
    return uniq


def default_roots(repo_root: str = ".") -> List[str]:
    return [repo_root, os.path.join(repo_root, "benchmarks", "results")]


def _series_sort_key(pt: Point) -> Tuple[int, str, str]:
    # Round-less artifacts (e.g. last_known_good) sort by timestamp
    # after round-stamped ones of the same vintage; use a large round
    # sentinel so explicit rounds dominate ordering.
    rnd = pt.round if pt.round is not None else 1 << 30
    return (rnd, pt.captured_utc, os.path.basename(pt.path))


def build_series(
    points: Iterable[Point],
) -> Dict[Tuple[str, str], List[Point]]:
    """Group points into (device_kind, metric) series, round-ordered."""
    series: Dict[Tuple[str, str], List[Point]] = {}
    for pt in points:
        series.setdefault((pt.device_kind, pt.metric), []).append(pt)
    for key in series:
        series[key].sort(key=_series_sort_key)
    return series


def find_regressions(
    series: Dict[Tuple[str, str], List[Point]], floor: float = DEFAULT_FLOOR
) -> List[Regression]:
    """Walk each series; name the FIRST artifact below floor × best."""
    out: List[Regression] = []
    for key, pts in sorted(series.items()):
        best: Optional[Point] = None
        for pt in pts:
            if best is not None and best.value > 0:
                ratio = pt.value / best.value
                if ratio < floor:
                    out.append(
                        Regression(
                            series=key,
                            path=pt.path,
                            value=pt.value,
                            best_value=best.value,
                            best_path=best.path,
                            ratio=ratio,
                            floor=floor,
                        )
                    )
                    break
            if best is None or pt.value > best.value:
                best = pt
    return out


@dataclasses.dataclass
class Report:
    points: List[Point]
    statuses: List[Status]
    notes: List[str]
    series: Dict[Tuple[str, str], List[Point]]
    regressions: List[Regression]
    floor: float

    def as_dict(self) -> Dict[str, Any]:
        return {
            "floor": self.floor,
            "series": {
                f"{dk}/{metric}": [p.as_dict() for p in pts]
                for (dk, metric), pts in sorted(self.series.items())
            },
            "statuses": [s.as_dict() for s in self.statuses],
            "notes": list(self.notes),
            "regressions": [r.as_dict() for r in self.regressions],
        }


def build_report(
    roots: Sequence[str], floor: float = DEFAULT_FLOOR
) -> Report:
    notes: List[str] = []
    statuses: List[Status] = []
    points: List[Point] = []
    for path in discover(roots):
        pt = load_artifact(path, notes, statuses)
        if pt is not None:
            points.append(pt)
    series = build_series(points)
    return Report(
        points=points,
        statuses=statuses,
        notes=notes,
        series=series,
        regressions=find_regressions(series, floor),
        floor=floor,
    )


def gate_attribution(
    roots: Sequence[str],
    *,
    metric: str,
    device_kind: str,
    measured_value: float,
    measured_path: str = "<this run>",
    floor: float = DEFAULT_FLOOR,
) -> Optional[Dict[str, Any]]:
    """Attribution hook for ``bench.py --gate``.

    Appends the just-measured point to its historical series and
    returns the first offending artifact in the combined trajectory
    (which may be a committed artifact that bent the curve earlier, or
    this very run).  Returns None when the trajectory is clean or
    history is unusable — the gate must never fail because attribution
    couldn't run.
    """
    try:
        report = build_report(roots, floor)
        pts = list(report.series.get((device_kind, metric), []))
        pts.append(
            Point(
                path=measured_path,
                round=None,
                metric=metric,
                value=float(measured_value),
                device_kind=device_kind,
            )
        )
        regs = find_regressions({(device_kind, metric): pts}, floor)
        return regs[0].as_dict() if regs else None
    except Exception:  # noqa: BLE001 — attribution is best-effort
        return None


# ---------------------------------------------------------------------------
# Rendering


def _fmt_val(v: float) -> str:
    if v >= 1000:
        return f"{v:,.0f}"
    if v >= 10:
        return f"{v:.1f}"
    return f"{v:.3g}"


def render_console(report: Report) -> str:
    lines: List[str] = []
    lines.append(f"bench trajectory — floor {report.floor:.2f}")
    for (dk, metric), pts in sorted(report.series.items()):
        lines.append(f"\n[{dk}] {metric}")
        best = 0.0
        for pt in pts:
            best = max(best, pt.value)
            ratio = pt.value / best if best > 0 else 1.0
            flag = "  " if ratio >= report.floor else "<<"
            rnd = f"r{pt.round:02d}" if pt.round is not None else "  ?"
            lines.append(
                f"  {rnd}  {_fmt_val(pt.value):>12} {pt.unit:<18}"
                f" x{ratio:4.2f} {flag} {os.path.basename(pt.path)}"
            )
    if report.statuses:
        lines.append("\nstatus artifacts (no numeric series):")
        for st in report.statuses:
            state = (
                "skipped" if st.skipped else ("ok" if st.ok else "failed")
            )
            lines.append(f"  {state:<8} {os.path.basename(st.path)}")
    if report.notes:
        lines.append("\nskipped artifacts:")
        for note in report.notes:
            lines.append(f"  - {note}")
    if report.regressions:
        lines.append("\nREGRESSIONS:")
        for r in report.regressions:
            lines.append(
                f"  [{r.series[0]}] {r.series[1]}: {os.path.basename(r.path)}"
                f" fell to {_fmt_val(r.value)} = {r.ratio:.2f}x of best"
                f" {_fmt_val(r.best_value)} ({os.path.basename(r.best_path)})"
                f" < floor {r.floor:.2f}"
            )
    else:
        lines.append("\nno regressions below floor.")
    return "\n".join(lines) + "\n"


def render_json(report: Report) -> str:
    return json.dumps(report.as_dict(), indent=2, sort_keys=True) + "\n"


def render_markdown(report: Report) -> str:
    lines: List[str] = ["# Bench trajectory", ""]
    lines.append(f"Regression floor: **{report.floor:.2f}×** best-so-far.")
    for (dk, metric), pts in sorted(report.series.items()):
        lines.append(f"\n## `{dk}` / `{metric}`\n")
        lines.append("| round | value | unit | vs best | artifact |")
        lines.append("|---|---|---|---|---|")
        best = 0.0
        for pt in pts:
            best = max(best, pt.value)
            ratio = pt.value / best if best > 0 else 1.0
            rnd = f"r{pt.round:02d}" if pt.round is not None else "—"
            mark = " ⚠" if ratio < report.floor else ""
            lines.append(
                f"| {rnd} | {_fmt_val(pt.value)} | {pt.unit} |"
                f" {ratio:.2f}×{mark} | `{os.path.basename(pt.path)}` |"
            )
    if report.regressions:
        lines.append("\n## Regressions\n")
        for r in report.regressions:
            lines.append(
                f"- **`{os.path.basename(r.path)}`** ({r.series[0]}/"
                f"{r.series[1]}): {_fmt_val(r.value)} is {r.ratio:.2f}× of"
                f" best `{os.path.basename(r.best_path)}`"
                f" ({_fmt_val(r.best_value)}), below floor {r.floor:.2f}."
            )
    else:
        lines.append("\nNo regressions below floor.")
    if report.notes:
        lines.append("\n## Skipped artifacts\n")
        for note in report.notes:
            lines.append(f"- {note}")
    return "\n".join(lines) + "\n"
