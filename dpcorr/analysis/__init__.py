"""dpcorr.analysis — AST-based invariant linter (docs/STATIC_ANALYSIS.md).

Run it as ``python -m dpcorr lint``; programmatic entry point is
:func:`run_lint`. Stdlib-only on purpose: the CI lint gate runs before
jax is installed and the module must import in well under a second.
"""

from dpcorr.analysis.core import (  # noqa: F401
    Checker,
    Module,
    Violation,
    apply_baseline,
    default_checkers,
    iter_py_files,
    load_baseline,
    run_lint,
    write_baseline,
)

__all__ = [
    "Checker", "Module", "Violation", "apply_baseline",
    "default_checkers", "iter_py_files", "load_baseline", "run_lint",
    "write_baseline",
]
