"""``python -m dpcorr lint`` — the CLI over :mod:`dpcorr.analysis`.

jax-free by construction (stdlib ``ast`` only): the CI lint job runs
it before any jax wheel is even installed, and locally it answers in
well under the 10 s gate (ISSUE 3 acceptance). Exit codes: 0 clean
(baselined findings included), 1 new violations (or ``--strict`` with
stale baseline entries), 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from dpcorr.analysis import core

#: the committed grandfather file at the repo root.
DEFAULT_BASELINE = ".dpcorr-lint-baseline.json"
#: what `python -m dpcorr lint` sweeps when no paths are given. bench.py
#: and benchmarks/ ride along for the hot-path sync rule (rules.sync) —
#: an accidental per-block sync in the measurement harness corrupts the
#: numbers it reports, which is how the r03→r04 halving hid.
DEFAULT_PATHS = ("dpcorr", "bench.py", "benchmarks")


def add_arguments(ap: argparse.ArgumentParser) -> None:
    """Register the lint flags on ``ap`` (shared between the
    standalone parser and the ``python -m dpcorr lint`` subparser)."""
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/directories to lint "
                         f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--root", default=None,
                    help="repo root paths are resolved against "
                         "(default: cwd)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} "
                         f"under --root when it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--write-baseline", action="store_true",
                    help="triage mode: write the current findings as "
                         "the new baseline and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated checker families to run "
                         "(rng,budget,locks,purity,rawdata,sync,"
                         "metrics; with --deep also lockorder,"
                         "durability,deepbudget,coverage; default: all)")
    ap.add_argument("--deep", action="store_true",
                    help="also run the interprocedural families over "
                         "the whole-repo call graph (lock-order "
                         "cycles, blocking-under-lock, durability, "
                         "deep budget, chaos coverage)")
    ap.add_argument("--witness", default=None, metavar="DIR",
                    help="diff runtime syncwatch witness artifacts in "
                         "DIR against the static lock model and exit "
                         "(1 on unpredicted edges, inversions or "
                         "observed cycles)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--strict", action="store_true",
                    help="also fail (exit 1) on stale baseline entries")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every rule id and description, exit 0")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="dpcorr lint",
        description="AST-based privacy/RNG/concurrency invariant "
                    "checker (docs/STATIC_ANALYSIS.md)")
    add_arguments(ap)
    return ap


def _list_rules() -> int:
    for checker in core.default_checkers(deep=True):
        print(f"{checker.name}:")
        for rule, desc in checker.rules.items():
            print(f"  {rule:<24} {desc}")
    return 0


def main(argv=None) -> int:
    return run(build_parser().parse_args(argv))


def run(args: argparse.Namespace) -> int:
    if args.list_rules:
        return _list_rules()
    root = os.path.abspath(args.root or os.getcwd())
    paths = args.paths or list(DEFAULT_PATHS)
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full):
            print(f"dpcorr lint: no such path: {p}", file=sys.stderr)
            return 2
    rule_filter = ([s.strip() for s in args.rules.split(",") if s.strip()]
                   if args.rules else None)
    if args.witness is not None:
        from dpcorr.analysis import witness

        return witness.run_witness_check(paths, root, args.witness,
                                         as_json=args.json)
    try:
        violations = core.run_lint(paths, root, rule_filter=rule_filter,
                                   deep=args.deep)
    except ValueError as e:
        print(f"dpcorr lint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    if args.write_baseline:
        core.write_baseline(violations, baseline_path)
        print(f"wrote {len(violations)} baseline entries to "
              f"{baseline_path}")
        return 0

    entries: list[dict] = []
    if not args.no_baseline and os.path.exists(baseline_path):
        entries = core.load_baseline(baseline_path)
    new, matched, stale = core.apply_baseline(violations, entries)

    if args.json:
        print(json.dumps({
            "new": [vars(v) for v in new],
            "baselined": matched,
            "stale_baseline_entries": stale,
        }, indent=2))
    else:
        for v in new:
            print(v.render())
            if v.code:
                print(f"    {v.code}")
        for e in stale:
            print(f"stale baseline entry (fixed? regenerate with "
                  f"--write-baseline): [{e['rule']}] {e['path']}: "
                  f"{e['code']}")
        summary = (f"{len(new)} new violation"
                   f"{'' if len(new) == 1 else 's'}")
        if matched:
            summary += f", {matched} baselined"
        if stale:
            summary += f", {len(stale)} stale baseline entries"
        print(summary)
    if new:
        return 1
    if stale and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
