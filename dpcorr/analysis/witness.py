"""``dpcorr lint --witness DIR`` — diff runtime lock order vs static.

:mod:`dpcorr.utils.syncwatch` records the acquisition-order graph a
live process actually walked and dumps one ``witness-<pid>.json`` per
process. This module replays those artifacts against the static lock
model (:meth:`ProjectModel.lock_model`) and gates on three conditions:

- **observed-but-unpredicted edge** — the process acquired lock B
  while holding lock A, but the static call-graph analysis never
  predicted that ordering. Either the model has a blind spot (fix the
  model) or the code grew a lock nesting nobody reviewed (fix the
  code). Both deserve a red build. An edge whose endpoint cannot be
  matched to any statically known lock site counts as unpredicted —
  an unknown lock is the model's biggest possible blind spot.
- **runtime inversion** — syncwatch saw A→B and B→A live in one run.
  That is a deadlock that happened not to interleave.
- **observed cycle** — the union of observed edges across all witness
  files contains a directed cycle, even if no single run inverted.

Witness sites are ``relpath:lineno`` of the lock *creation* frame;
the static model records the same site for the enclosing assignment.
Multi-line constructor calls can put those a line or two apart, so
matching tolerates a small same-file line delta.

jax-free (stdlib + the analysis package only): the CI lint job runs
this gate in the container that deliberately has no jax wheel.
"""

from __future__ import annotations

import glob
import json
import os
import sys

from dpcorr.analysis.core import Module, iter_py_files

#: a runtime creation site within this many lines of a static lock
#: site (same file) is the same lock.
_LINE_SLACK = 2


def _build_lock_model(paths, root: str) -> dict:
    from dpcorr.analysis.callgraph import ProjectModel

    modules = []
    for relpath in iter_py_files(paths, root):
        full = os.path.join(root, relpath)
        with open(full, encoding="utf-8") as f:
            source = f.read()
        try:
            modules.append(Module(full, relpath, source))
        except SyntaxError:
            continue
    return ProjectModel(modules, root).lock_model()


def _site_index(lock_model: dict) -> dict:
    """``relpath -> [(lineno, lock_id)]`` over every static lock site."""
    index: dict = {}
    for lid, info in lock_model["locks"].items():
        for site in info["sites"]:
            path, _, line = site.rpartition(":")
            index.setdefault(path, []).append((int(line), lid))
    for rows in index.values():
        rows.sort()
    return index


def _resolve_site(site: str, index: dict) -> str | None:
    """Static lock id for a runtime creation site, or None."""
    path, _, line_s = site.rpartition(":")
    rows = index.get(path)
    if not rows:
        return None
    line = int(line_s)
    best = None
    for lineno, lid in rows:
        delta = abs(lineno - line)
        if delta <= _LINE_SLACK and (best is None or delta < best[0]):
            best = (delta, lid)
    return best[1] if best else None


def _find_cycle(edges: set) -> list | None:
    """One directed cycle in ``edges`` (as a node list), or None."""
    adj: dict = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    visiting: dict = {}  # node -> position on the current DFS path
    done: set = set()

    def dfs(node, path):
        visiting[node] = len(path)
        path.append(node)
        for nxt in adj.get(node, ()):
            if nxt in visiting:
                return path[visiting[nxt]:] + [nxt]
            if nxt not in done:
                found = dfs(nxt, path)
                if found:
                    return found
        path.pop()
        del visiting[node]
        done.add(node)
        return None

    for start in sorted(adj):
        if start not in done:
            found = dfs(start, [])
            if found:
                return found
    return None


def run_witness_check(paths, root: str, witness_dir: str,
                      as_json: bool = False) -> int:
    """Gate described in the module docstring. Returns the process
    exit code: 0 clean, 1 witness contradicts the model, 2 usage
    (missing directory / no artifacts — a smoke that produced no
    witness is a broken smoke, not a clean one)."""
    if not os.path.isdir(witness_dir):
        print(f"dpcorr lint: witness dir not found: {witness_dir}",
              file=sys.stderr)
        return 2
    files = sorted(glob.glob(os.path.join(witness_dir, "witness-*.json")))
    if not files:
        print(f"dpcorr lint: no witness-*.json artifacts in "
              f"{witness_dir} (was DPCORR_SYNCWATCH=1 exported?)",
              file=sys.stderr)
        return 2

    lock_model = _build_lock_model(paths, root)
    index = _site_index(lock_model)
    static_edges = {tuple(e) for e in lock_model["edges"]}

    observed: dict = {}      # (a_id, b_id) -> first witness file
    unpredicted: list = []
    unknown_sites: set = set()
    inversions: list = []
    for path in files:
        with open(path, encoding="utf-8") as fh:
            art = json.load(fh)
        for inv in art.get("inversions", []):
            inversions.append({**inv, "witness": os.path.basename(path)})
        for a_site, b_site in art.get("edges", []):
            a = _resolve_site(a_site, index)
            b = _resolve_site(b_site, index)
            for site, lid in ((a_site, a), (b_site, b)):
                if lid is None:
                    unknown_sites.add(site)
            a = a or f"?{a_site}"
            b = b or f"?{b_site}"
            if a == b:
                continue  # two sites of one lock: reentrancy, not order
            if (a, b) not in observed:
                observed[(a, b)] = os.path.basename(path)
                if (a, b) not in static_edges:
                    unpredicted.append(
                        {"edge": [a, b],
                         "sites": [a_site, b_site],
                         "witness": os.path.basename(path)})
    cycle = _find_cycle(set(observed))

    ok = not unpredicted and not inversions and cycle is None
    report = {
        "witness_files": [os.path.basename(p) for p in files],
        "observed_edges": sorted([a, b] for (a, b) in observed),
        "static_edges": sorted(map(list, static_edges)),
        "unpredicted_edges": unpredicted,
        "unknown_sites": sorted(unknown_sites),
        "inversions": inversions,
        "observed_cycle": cycle,
        "ok": ok,
    }
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        print(f"witness: {len(files)} artifact(s), "
              f"{len(observed)} observed edge(s), "
              f"{len(static_edges)} statically predicted")
        for u in unpredicted:
            a, b = u["edge"]
            print(f"observed-but-unpredicted lock order: {a} -> {b}")
            print(f"    creation sites {u['sites'][0]} -> "
                  f"{u['sites'][1]} ({u['witness']})")
        for inv in inversions:
            print(f"runtime lock-order inversion: {inv['held']} -> "
                  f"{inv['acquiring']} on thread {inv['thread']} "
                  f"({inv['witness']})")
        if cycle:
            print("observed lock-order cycle: " + " -> ".join(cycle))
        print("witness: " + ("clean — runtime order within the static "
                             "model" if ok else "FAILED"))
    return 0 if ok else 1
