"""Interprocedural model: repo-wide call graph + lock-acquisition facts.

The per-module checkers (rules/) reason about one function at a time;
the ``--deep`` pass (docs/STATIC_ANALYSIS.md §Deep analysis) reasons
about the *composition*: which locks can be held when a call chain
reaches an ``fsync``, whether two threads can acquire the same pair of
locks in opposite orders, whether a charge in one function dominates an
enqueue three calls away. This module builds the shared substrate, all
stdlib ``ast`` (the linter stays jax-free):

- **Lock identities.** Every ``threading.Lock()``/``RLock()`` created
  in the linted tree becomes a lock id named after its home
  (``dpcorr.serve.ledger.PrivacyLedger._lock``,
  ``dpcorr.chaos._lock``), carrying its creation site(s) so the
  runtime witness (utils/syncwatch.py) can map an observed lock back
  to the static model. ``threading.Condition(self._lock)`` aliases the
  wrapped lock — ``with self._cond`` acquires ``_lock``.
- **Call graph.** Calls resolve through lightweight type facts:
  ``self.x = Cls(...)`` and annotated parameters/attributes type the
  receiver; plain names resolve through imports and module scope;
  a name-unique method is matched as a last resort (never for generic
  names like ``append``). Unresolved calls stay unresolved — the
  analysis under-approximates rather than guesses.
- **Held-lock tracking.** Each function is scanned once, tracking the
  lexically-held lock set through ``with`` blocks (closures and
  lambdas escape the guard, as in rules/locks.py), recording every
  call site, lock acquisition and *effect* (fsync/subprocess/socket/
  ``.result()``/``join()``/``os.replace``/sweep/quarantine) together
  with the locks held at that point.
- **Closures.** :meth:`ProjectModel.transitive_acquires` and
  :meth:`~ProjectModel.transitive_effects` propagate those facts
  through the call graph (depth-capped, memoized), producing the
  file:line chains the findings report. The static lock-order graph
  (:meth:`~ProjectModel.lock_order_edges`) is every (held → acquired)
  pair, lexical or call-mediated; :meth:`~ProjectModel.lock_cycles`
  reports its cycles.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Sequence

from dpcorr.analysis.core import Module, attr_chain, imported_names

#: interprocedural chains are followed (and reported) this deep at most.
MAX_DEPTH = 6

#: effect kinds that block the calling thread (the ``blocking-under-lock``
#: rule keys on these; ``replace``/``sweep``/``quarantine`` are tracked
#: for the durability rule and are not blocking).
BLOCKING_KINDS = frozenset({
    "fsync", "subprocess", "socket", "result", "join", "sleep", "wait",
})

#: method names too generic for the unique-name fallback resolver — a
#: stray ``lst.append`` must never link to ``IngestWAL.append``.
_GENERIC_METHOD_NAMES = frozenset({
    "acquire", "add", "append", "apply", "charge", "clear", "close",
    "copy", "dump", "dumps", "flush", "get", "items", "join", "keys",
    "add_done_callback", "cancel", "done", "load", "loads", "main",
    "merge", "open", "point", "pop", "put", "read", "record", "recv",
    "refund", "release", "render", "reset", "result", "run", "send",
    "set_exception", "set_result", "start", "stop", "submit",
    "update", "values", "wait", "write",
})

_SOCKET_METHODS = frozenset({
    "accept", "connect", "create_connection", "makefile", "recv",
    "recvfrom", "recv_into", "sendall",
})
_SUBPROCESS_FNS = frozenset({
    "Popen", "call", "check_call", "check_output", "run",
})


@dataclasses.dataclass
class CallSite:
    """One call expression inside a function body."""
    lineno: int
    text: str                     # dotted call text ("self.wal.append")
    target: str | None            # resolved FuncKey, or None
    held: tuple[str, ...]         # lock ids lexically held at the call


@dataclasses.dataclass
class Acquire:
    """One ``with <lock>`` acquisition site."""
    lock_id: str
    lineno: int
    held: tuple[str, ...]         # lock ids already held when acquiring


@dataclasses.dataclass
class Effect:
    """One direct side effect (fsync, subprocess, os.replace, ...)."""
    kind: str
    lineno: int
    text: str
    held: tuple[str, ...]


@dataclasses.dataclass
class LockInfo:
    lock_id: str
    kind: str                     # "lock" | "rlock" | "condition"
    sites: list[str]              # "relpath:lineno" creation sites


class ClassInfo:
    def __init__(self, key: str, relpath: str, name: str,
                 node: ast.ClassDef):
        self.key = key
        self.relpath = relpath
        self.name = name
        self.node = node
        self.methods: dict[str, str] = {}      # method name -> FuncKey
        self.attr_types: dict[str, str] = {}   # attr -> ClassKey/pseudo
        self.lock_attrs: dict[str, str] = {}   # attr -> lock id
        self.base_names: list[str] = [
            b.id for b in node.bases if isinstance(b, ast.Name)]


class FunctionInfo:
    def __init__(self, key: str, relpath: str, qualname: str,
                 node: ast.AST, module: Module, cls_key: str | None):
        self.key = key
        self.relpath = relpath
        self.qualname = qualname
        self.name = node.name
        self.lineno = node.lineno
        self.node = node
        self.module = module
        self.cls_key = cls_key
        self.calls: list[CallSite] = []
        self.refs: set[str] = set()            # referenced FuncKeys
        self.acquires: list[Acquire] = []
        self.effects: list[Effect] = []

    def site(self, lineno: int | None = None) -> str:
        return f"{self.relpath}:{lineno or self.lineno} ({self.qualname})"


class ProjectModel:
    """All linted modules, resolved into one interprocedural model."""

    def __init__(self, modules: Sequence[Module], root: str):
        self.root = root
        self.modules = list(modules)
        self.by_relpath: dict[str, Module] = {
            m.relpath: m for m in self.modules}
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.module_classes: dict[str, dict[str, str]] = {}
        self.module_functions: dict[str, dict[str, str]] = {}
        self.module_locks: dict[str, dict[str, str]] = {}
        self.locks: dict[str, LockInfo] = {}
        self.lock_sites: dict[str, str] = {}   # "relpath:line" -> id
        self.method_index: dict[str, list[str]] = {}
        self._imports = {m.relpath: imported_names(m.tree)
                         for m in self.modules}
        self._dot_to_relpath = {
            self._dot(m.relpath): m.relpath for m in self.modules}
        self._acq_memo: dict[str, dict] = {}
        self._eff_memo: dict[str, dict] = {}
        self._edges: dict[tuple[str, str], tuple[str, ...]] | None = None
        for m in self.modules:
            self._collect(m)
        for m in self.modules:
            self._collect_locks_and_types(m)
        for fi in self.functions.values():
            self._scan_function(fi)

    # ------------------------------------------------------ indexing ----
    @staticmethod
    def _dot(relpath: str) -> str:
        return relpath[:-3].replace("/", ".") if relpath.endswith(".py") \
            else relpath.replace("/", ".")

    def _collect(self, module: Module) -> None:
        relpath = module.relpath
        self.module_classes[relpath] = {}
        self.module_functions[relpath] = {}
        self.module_locks[relpath] = {}

        def walk(body, cls_info, ctx_cls, prefix, parent_fn):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    key = f"{relpath}::{prefix}{node.name}"
                    ci = ClassInfo(key, relpath, node.name, node)
                    self.classes[key] = ci
                    if not prefix:
                        self.module_classes[relpath][node.name] = key
                    walk(node.body, ci, key, f"{prefix}{node.name}.",
                         None)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    qual = f"{prefix}{node.name}"
                    key = f"{relpath}::{qual}"
                    this_cls = cls_info.key if cls_info else ctx_cls
                    fi = FunctionInfo(key, relpath, qual, node, module,
                                      this_cls)
                    self.functions[key] = fi
                    if cls_info is not None:
                        cls_info.methods[node.name] = key
                        self.method_index.setdefault(
                            node.name, []).append(key)
                    elif not prefix:
                        self.module_functions[relpath][node.name] = key
                    if parent_fn is not None:
                        parent_fn.refs.add(key)
                    walk(node.body, None, this_cls, f"{qual}.", fi)

        walk(module.tree.body, None, None, "", None)

    # --------------------------------------------- locks & attr types ----
    def _factory(self, relpath: str, call: ast.Call) -> str | None:
        """"lock"/"rlock"/"condition"/"event"/"thread" for threading
        factory calls, else None."""
        dotted = self._dotted(relpath, attr_chain(call.func))
        return {
            "threading.Lock": "lock", "threading.RLock": "rlock",
            "threading.Condition": "condition",
            "threading.Event": "event", "threading.Thread": "thread",
        }.get(dotted)

    def _dotted(self, relpath: str, chain: tuple[str, ...]) -> str:
        if not chain:
            return ""
        imports = self._imports[relpath]
        if chain[0] in imports:
            return ".".join((imports[chain[0]],) + chain[1:])
        return ".".join(chain)

    def _register_lock(self, lock_id: str, kind: str, relpath: str,
                       lineno: int) -> None:
        site = f"{relpath}:{lineno}"
        info = self.locks.setdefault(lock_id, LockInfo(lock_id, kind, []))
        if site not in info.sites:
            info.sites.append(site)
        self.lock_sites[site] = lock_id

    def _collect_locks_and_types(self, module: Module) -> None:
        relpath = module.relpath
        moddot = self._dot(relpath)
        # module-level locks (chaos._lock, obs.trace._global_lock, ...)
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                kind = self._factory(relpath, node.value)
                name = node.targets[0].id
                if kind in ("lock", "rlock"):
                    lid = f"{moddot}.{name}"
                    self._register_lock(lid, kind, relpath,
                                        node.value.lineno)
                    self.module_locks[relpath][name] = lid
                elif kind == "condition":
                    args = node.value.args
                    if args and isinstance(args[0], ast.Name) and \
                            args[0].id in self.module_locks[relpath]:
                        self.module_locks[relpath][name] = \
                            self.module_locks[relpath][args[0].id]
                    else:
                        lid = f"{moddot}.{name}"
                        self._register_lock(lid, "condition", relpath,
                                            node.value.lineno)
                        self.module_locks[relpath][name] = lid
        for ci in self.classes.values():
            if ci.relpath == relpath:
                self._collect_class(module, ci, moddot)

    def _collect_class(self, module: Module, ci: ClassInfo,
                       moddot: str) -> None:
        relpath = module.relpath
        assigns: list[tuple[str, ast.Call]] = []
        for node in ast.walk(ci.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                attr = None
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    attr = t.attr
                elif isinstance(t, ast.Name) and node in ci.node.body:
                    attr = t.id        # class-level shared attribute
                if attr is None:
                    continue
                if isinstance(node.value, ast.Call):
                    assigns.append((attr, node.value))
                elif isinstance(node.value, ast.Name):
                    # self.x = param — typed by the param's annotation
                    ck = self._param_type(ci, node, node.value.id)
                    if ck:
                        ci.attr_types.setdefault(attr, ck)
                elif isinstance(node.value, ast.BoolOp):
                    # self.x = param or Cls() — either operand types it
                    for operand in node.value.values:
                        if isinstance(operand, ast.Call):
                            assigns.append((attr, operand))
                        elif isinstance(operand, ast.Name):
                            ck = self._param_type(ci, node, operand.id)
                            if ck:
                                ci.attr_types.setdefault(attr, ck)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Attribute) and \
                    isinstance(node.target.value, ast.Name) and \
                    node.target.value.id == "self":
                ck = self._resolve_annotation(relpath, node.annotation)
                if ck:
                    ci.attr_types.setdefault(node.target.attr, ck)
                if isinstance(node.value, ast.Call):
                    assigns.append((node.target.attr, node.value))
        # pass 1: plain locks; pass 2: conditions may alias them
        for attr, call in assigns:
            kind = self._factory(relpath, call)
            if kind in ("lock", "rlock"):
                lid = f"{moddot}.{ci.name}.{attr}"
                self._register_lock(lid, kind, relpath, call.lineno)
                ci.lock_attrs[attr] = lid
        for attr, call in assigns:
            kind = self._factory(relpath, call)
            if kind == "condition":
                arg = call.args[0] if call.args else None
                wrapped = None
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "self":
                    wrapped = ci.lock_attrs.get(arg.attr)
                if wrapped:
                    ci.lock_attrs[attr] = wrapped
                else:
                    lid = f"{moddot}.{ci.name}.{attr}"
                    self._register_lock(lid, "condition", relpath,
                                        call.lineno)
                    ci.lock_attrs[attr] = lid
            elif kind in ("event", "thread"):
                ci.attr_types.setdefault(attr, f"threading.{kind}")
            elif kind is None:
                ck = self._class_of_call(relpath, call)
                if ck:
                    ci.attr_types.setdefault(attr, ck)
        # pass 3: factory-method returns — `self.x = r.counter(...)`
        # with `def counter(...) -> Counter` types the attribute; local
        # intermediates (`r = registry()`) are typed in source order so
        # the chain resolves
        for mkey in ci.methods.values():
            fn = self.functions.get(mkey)
            if fn is None:
                continue
            local: dict[str, str] = {}
            args = fn.node.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.annotation is not None:
                    ck = self._resolve_annotation(relpath, a.annotation)
                    if ck:
                        local[a.arg] = ck
            for node in ast.walk(fn.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.value, ast.Call)):
                    continue
                t = node.targets[0]
                ck = self._class_of_call(relpath, node.value,
                                         ctx_cls=ci.key,
                                         local_types=local)
                if not ck:
                    continue
                if isinstance(t, ast.Name):
                    local[t.id] = ck
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self" and \
                        t.attr not in ci.lock_attrs:
                    ci.attr_types.setdefault(t.attr, ck)

    def _param_type(self, ci: ClassInfo, assign: ast.AST,
                    pname: str) -> str | None:
        """Type of ``self.x = pname`` from the enclosing function's
        annotated parameter list."""
        for name, key in ci.methods.items():
            fi = self.functions.get(key)
            if fi is None or not any(
                    n is assign for n in ast.walk(fi.node)):
                continue
            args = fi.node.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg == pname and a.annotation is not None:
                    return self._resolve_annotation(ci.relpath,
                                                    a.annotation)
        return None

    def _resolve_annotation(self, relpath: str,
                            ann: ast.AST) -> str | None:
        """``Cls`` / ``mod.Cls`` / ``Optional[Cls]`` / ``Cls | None`` /
        ``"Cls"`` → ClassKey (or a ``threading.*`` pseudo-key)."""
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return self._resolve_annotation(relpath, ann.left) or \
                self._resolve_annotation(relpath, ann.right)
        if isinstance(ann, ast.Subscript):  # Optional[X] / list[X]: try X
            chain = attr_chain(ann.value)
            if chain and chain[-1] in ("Optional", "Union"):
                inner = ann.slice
                if isinstance(inner, ast.Tuple):
                    for el in inner.elts:
                        ck = self._resolve_annotation(relpath, el)
                        if ck:
                            return ck
                    return None
                return self._resolve_annotation(relpath, inner)
            return None
        chain = attr_chain(ann)
        if not chain:
            return None
        return self._resolve_class_chain(relpath, chain)

    def _resolve_class_chain(self, relpath: str,
                             chain: tuple[str, ...]) -> str | None:
        if len(chain) == 1 and \
                chain[0] in self.module_classes.get(relpath, {}):
            return self.module_classes[relpath][chain[0]]
        dotted = self._dotted(relpath, chain)
        if dotted in ("threading.Event", "threading.Thread"):
            return "threading." + chain[-1].lower()
        mod, _, cls = dotted.rpartition(".")
        target = self._dot_to_relpath.get(mod)
        if target:
            return self.module_classes.get(target, {}).get(cls)
        return None

    def _class_of_call(self, relpath: str, call: ast.Call,
                       ctx_cls: str | None = None,
                       local_types: dict[str, str] | None = None,
                       ) -> str | None:
        kind = self._factory(relpath, call)
        if kind in ("event", "thread"):
            return f"threading.{kind}"
        chain = attr_chain(call.func)
        if not chain:
            return None
        ck = self._resolve_class_chain(relpath, chain)
        if ck:
            return ck
        # factory-method fallback: a call that resolves to a function
        # whose return annotation names a known class types the result
        # (the metrics registry builds every instrument this way, and
        # instrument mutators all take _Metric._lock — without this
        # the lock model is blind to every `held -> metric` edge, which
        # is exactly what the syncwatch witness caught)
        key = self.resolve_call(relpath, ctx_cls, local_types or {},
                                chain)
        if key:
            fn = self.functions.get(key)
            if fn is not None and fn.name != "__init__" and \
                    getattr(fn.node, "returns", None) is not None:
                return self._resolve_annotation(fn.relpath,
                                                fn.node.returns)
        return None

    # ------------------------------------------------ call resolution ----
    def _mro(self, ci: ClassInfo) -> list[ClassInfo]:
        out: list[ClassInfo] = []
        queue = [ci]
        local = self.module_classes.get(ci.relpath, {})
        while queue:
            c = queue.pop(0)
            if c in out:
                continue
            out.append(c)
            for b in c.base_names:
                if b in local and self.classes[local[b]] not in out:
                    queue.append(self.classes[local[b]])
        return out

    def _method(self, ci: ClassInfo, name: str) -> str | None:
        for c in self._mro(ci):
            if name in c.methods:
                return c.methods[name]
        return None

    def _attr_type(self, ci: ClassInfo, attr: str) -> str | None:
        for c in self._mro(ci):
            if attr in c.attr_types:
                return c.attr_types[attr]
        return None

    def _effective_lock_attrs(self, ci: ClassInfo) -> dict[str, str]:
        out: dict[str, str] = {}
        for c in reversed(self._mro(ci)):
            out.update(c.lock_attrs)
        return out

    def resolve_call(self, relpath: str, ctx_cls: str | None,
                     local_types: dict[str, str],
                     chain: tuple[str, ...]) -> str | None:
        """Resolve a called name chain to a FuncKey, or None."""
        if not chain:
            return None
        ci = self.classes.get(ctx_cls) if ctx_cls else None
        if chain[0] == "self" and ci is not None:
            if len(chain) == 2:
                return self._method(ci, chain[1])
            if len(chain) == 3:
                ck = self._attr_type(ci, chain[1])
                if ck in self.classes:
                    return self._method(self.classes[ck], chain[2])
            return None
        if len(chain) == 1:
            name = chain[0]
            if name in self.module_functions.get(relpath, {}):
                return self.module_functions[relpath][name]
            ck = self._resolve_class_chain(relpath, chain)
            if ck in self.classes:
                return self._method(self.classes[ck], "__init__")
            dotted = self._imports[relpath].get(name)
            if dotted:
                return self._resolve_dotted_callable(dotted)
            return None
        head = chain[0]
        if head in local_types and len(chain) == 2:
            ck = local_types[head]
            if ck in self.classes:
                return self._method(self.classes[ck], chain[1])
            return None
        if head in self._imports[relpath]:
            return self._resolve_dotted_callable(
                self._dotted(relpath, chain))
        if head in self.module_classes.get(relpath, {}) \
                and len(chain) == 2:
            return self._method(
                self.classes[self.module_classes[relpath][head]],
                chain[1])
        # unique-name fallback (never for generic method names)
        name = chain[-1]
        if name not in _GENERIC_METHOD_NAMES:
            cands = self.method_index.get(name, [])
            if len(cands) == 1:
                return cands[0]
        return None

    def _resolve_dotted_callable(self, dotted: str) -> str | None:
        mod, _, name = dotted.rpartition(".")
        target = self._dot_to_relpath.get(mod)
        if target:
            if name in self.module_functions.get(target, {}):
                return self.module_functions[target][name]
            ck = self.module_classes.get(target, {}).get(name)
            if ck:
                return self._method(self.classes[ck], "__init__")
        # from m import Cls; Cls.method / Cls(...) resolved one up
        mod2, _, cls = mod.rpartition(".")
        target = self._dot_to_relpath.get(mod2)
        if target:
            ck = self.module_classes.get(target, {}).get(cls)
            if ck:
                return self._method(self.classes[ck], name)
        return None

    # ------------------------------------------------- function scan ----
    def _scan_function(self, fi: FunctionInfo) -> None:
        relpath = fi.relpath
        ci = self.classes.get(fi.cls_key) if fi.cls_key else None
        lock_attrs = self._effective_lock_attrs(ci) if ci else {}
        mod_locks = self.module_locks.get(relpath, {})
        local_types: dict[str, str] = {}
        args = fi.node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if a.annotation is not None:
                ck = self._resolve_annotation(relpath, a.annotation)
                if ck:
                    local_types[a.arg] = ck
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                ck = self._class_of_call(relpath, node.value,
                                         ctx_cls=fi.cls_key,
                                         local_types=local_types)
                if ck:
                    local_types[node.targets[0].id] = ck

        def resolve_lock(expr) -> str | None:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self":
                return lock_attrs.get(expr.attr)
            if isinstance(expr, ast.Name):
                return mod_locks.get(expr.id)
            return None

        def effect_kind(chain: tuple[str, ...], call: ast.Call,
                        resolved: str | None) -> str | None:
            if not chain:
                return None
            last = chain[-1]
            if last == "fsync":
                return "fsync"
            if "sweep_stale_tmp" in last:
                return "sweep"
            if "quarantine" in last:
                return "quarantine"
            if last == "replace" and len(chain) >= 2:
                return "replace"
            if resolved is not None:
                return None          # a project call: effects come
            dotted = self._dotted(relpath, chain)  # transitively
            if dotted == "time.sleep":
                return "sleep"
            if dotted.startswith("subprocess.") and \
                    last in _SUBPROCESS_FNS:
                return "subprocess"
            if last in _SOCKET_METHODS:
                return "socket"
            if last == "result" and len(chain) >= 2:
                return "result"
            rcv_type = None
            if len(chain) == 2 and chain[0] in local_types:
                rcv_type = local_types[chain[0]]
            elif len(chain) == 2 and chain[0] == "self":
                rcv_type = None
            elif chain[0] == "self" and len(chain) == 3 and ci:
                rcv_type = self._attr_type(ci, chain[1])
            if last == "join" and len(chain) >= 2:
                if rcv_type == "threading.thread" or any(
                        "thread" in p.lower() or "worker" in p.lower()
                        for p in chain[:-1]):
                    return "join"
            if last == "wait" and rcv_type == "threading.event":
                return "wait"
            return None

        def visit_expr(node, held: tuple[str, ...]) -> None:
            if not isinstance(node, ast.AST):
                return
            if isinstance(node, ast.Lambda):
                visit_expr(node.body, ())      # runs later, unguarded
                return
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                target = self.resolve_call(relpath, fi.cls_key,
                                           local_types, chain)
                text = ".".join(chain) if chain else "<call>"
                fi.calls.append(CallSite(node.lineno, text, target,
                                         held))
                kind = effect_kind(chain, node, target)
                if kind:
                    fi.effects.append(Effect(kind, node.lineno, text,
                                             held))
            elif isinstance(node, (ast.Attribute, ast.Name)) and \
                    isinstance(getattr(node, "ctx", None), ast.Load):
                chain = attr_chain(node)
                if chain and chain[-1] not in _GENERIC_METHOD_NAMES:
                    t = self.resolve_call(relpath, fi.cls_key,
                                          local_types, chain)
                    if t:
                        fi.refs.add(t)   # e.g. Thread(target=self._run)
            for child in ast.iter_child_nodes(node):
                visit_expr(child, held)

        def walk_stmts(stmts, held: tuple[str, ...]) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue          # separate FunctionInfo (+ ref)
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    now = list(held)
                    for it in stmt.items:
                        visit_expr(it.context_expr, tuple(now))
                        lid = resolve_lock(it.context_expr)
                        if lid:
                            fi.acquires.append(Acquire(
                                lid, it.context_expr.lineno,
                                tuple(now)))
                            if lid not in now:
                                now.append(lid)
                    walk_stmts(stmt.body, tuple(now))
                    continue
                for field, value in ast.iter_fields(stmt):
                    if field in ("body", "orelse", "finalbody") and \
                            isinstance(value, list):
                        walk_stmts(value, held)
                    elif field == "handlers":
                        for h in value:
                            walk_stmts(h.body, held)
                    elif isinstance(value, list):
                        for v in value:
                            visit_expr(v, held)
                    else:
                        visit_expr(value, held)

        walk_stmts(fi.node.body, ())

    # ----------------------------------------------------- closures ----
    def transitive_acquires(self, key: str,
                            _stack: frozenset[str] = frozenset(),
                            ) -> dict[str, tuple[str, ...]]:
        """lock id → hop chain for every lock ``key`` (or anything it
        calls, depth-capped) may acquire."""
        if key in self._acq_memo:
            return self._acq_memo[key]
        if key in _stack or len(_stack) >= MAX_DEPTH:
            return {}
        fi = self.functions.get(key)
        if fi is None:
            return {}
        out: dict[str, tuple[str, ...]] = {}
        for acq in fi.acquires:
            out.setdefault(acq.lock_id, (fi.site(acq.lineno),))
        for cs in fi.calls:
            if cs.target is None:
                continue
            sub = self.transitive_acquires(cs.target,
                                           _stack | {key})
            for lid, chain in sub.items():
                out.setdefault(
                    lid, (fi.site(cs.lineno),) + chain)
        if not _stack:
            self._acq_memo[key] = out
        return out

    def transitive_effects(self, key: str,
                           _stack: frozenset[str] = frozenset(),
                           ) -> dict[str, tuple[str, ...]]:
        """effect kind → hop chain for every effect reachable from
        ``key`` through the call graph."""
        if key in self._eff_memo:
            return self._eff_memo[key]
        if key in _stack or len(_stack) >= MAX_DEPTH:
            return {}
        fi = self.functions.get(key)
        if fi is None:
            return {}
        out: dict[str, tuple[str, ...]] = {}
        for eff in fi.effects:
            out.setdefault(eff.kind,
                           (fi.site(eff.lineno) + f" {eff.text}",))
        for cs in fi.calls:
            if cs.target is None:
                continue
            sub = self.transitive_effects(cs.target, _stack | {key})
            for kind, chain in sub.items():
                out.setdefault(kind, (fi.site(cs.lineno),) + chain)
        if not _stack:
            self._eff_memo[key] = out
        return out

    def reachable(self, roots: Iterable[str]) -> set[str]:
        """Every FuncKey reachable from ``roots`` via calls or
        function references (``Thread(target=...)`` counts)."""
        seen: set[str] = set()
        queue = [r for r in roots if r in self.functions]
        while queue:
            key = queue.pop()
            if key in seen:
                continue
            seen.add(key)
            fi = self.functions[key]
            for cs in fi.calls:
                if cs.target and cs.target not in seen:
                    queue.append(cs.target)
            for ref in fi.refs:
                if ref not in seen:
                    queue.append(ref)
        return seen

    # ----------------------------------------------- lock-order graph ----
    def lock_order_edges(self) -> dict[tuple[str, str], tuple[str, ...]]:
        """(held, acquired) → representative file:line chain. The edge
        set is the static prediction the runtime witness diffs against;
        a cycle in it is a potential deadlock."""
        if self._edges is not None:
            return self._edges
        edges: dict[tuple[str, str], tuple[str, ...]] = {}
        for fi in self.functions.values():
            for acq in fi.acquires:
                for a in acq.held:
                    edges.setdefault((a, acq.lock_id),
                                     (fi.site(acq.lineno),))
            for cs in fi.calls:
                if not cs.held or cs.target is None:
                    continue
                sub = self.transitive_acquires(cs.target)
                for b, chain in sub.items():
                    for a in cs.held:
                        edges.setdefault(
                            (a, b), (fi.site(cs.lineno),) + chain)
        self._edges = edges
        return edges

    def lock_cycles(self) -> list[list[tuple[str, str, tuple[str, ...]]]]:
        """Cycles in the lock-order graph, each as a list of
        (held, acquired, chain) edges. Reentrant self-edges on RLocks
        and Conditions are legal and skipped; a self-edge on a plain
        Lock is a guaranteed self-deadlock and is reported as a
        1-cycle."""
        edges = self.lock_order_edges()
        cycles: list[list[tuple[str, str, tuple[str, ...]]]] = []
        adj: dict[str, list[str]] = {}
        for (a, b), chain in sorted(edges.items()):
            if a == b:
                kind = self.locks.get(a, LockInfo(a, "lock", [])).kind
                if kind == "lock":
                    cycles.append([(a, b, chain)])
                continue
            adj.setdefault(a, []).append(b)
        # DFS cycle enumeration (first cycle per SCC is enough for a
        # finding; the graph is tiny)
        seen_cycles: set[tuple[str, ...]] = set()
        for start in sorted(adj):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in adj.get(node, []):
                    if nxt == start and len(path) > 1:
                        key = tuple(sorted(path))
                        if key in seen_cycles:
                            continue
                        seen_cycles.add(key)
                        cyc = []
                        loop = path + [start]
                        for a, b in zip(loop, loop[1:]):
                            cyc.append((a, b, edges[(a, b)]))
                        cycles.append(cyc)
                    elif nxt not in path and len(path) < MAX_DEPTH:
                        stack.append((nxt, path + [nxt]))
        return cycles

    # --------------------------------------------- witness interface ----
    def lock_model(self) -> dict:
        """The static model the runtime witness diff consumes
        (analysis/witness.py): lock ids with creation sites, plus the
        predicted lock-order edge set."""
        return {
            "locks": {lid: {"kind": info.kind,
                            "sites": sorted(info.sites)}
                      for lid, info in sorted(self.locks.items())},
            "edges": sorted([a, b] for (a, b)
                            in self.lock_order_edges()),
        }
