"""Linter core: parsed modules, checker registry, suppressions, baseline.

``python -m dpcorr lint`` is a plugin-based static pass over the
repo's own source enforcing the invariants the runtime layers can only
uphold by convention (docs/STATIC_ANALYSIS.md):

- **RNG hygiene** (analysis.rules.rng) — the named-stream key-tree
  discipline of ``dpcorr.utils.rng``.
- **Budget discipline** (analysis.rules.budget) — charge-before-noise
  and refund-on-refusal in the serving layer.
- **Lock discipline** (analysis.rules.locks) — ``# guarded by: _lock``
  attribute declarations checked against every access site.
- **jit purity** (analysis.rules.purity) — no host side effects or
  closure mutation inside traced (``jit``/``vmap``/``lax.map``/
  ``pallas_call``) functions.

Everything here is stdlib-only (``ast``): the linter must run in a
jax-free CI job and inside ``python -m dpcorr lint`` without paying —
or depending on — a jax import (the ``doctor``/``obs budget`` rule,
__main__.py ``jax_free``).

Two escape hatches, both explicit and reviewable:

- a line comment ``# dpcorr-lint: ignore[rule-a,rule-b]`` (or a bare
  ``ignore`` for any rule) suppresses findings on that line, or — as a
  standalone comment — on the line below it;
- a committed baseline file (``.dpcorr-lint-baseline.json``) grandfathers
  triaged pre-existing findings so the CI gate fails only on *new*
  violations. Entries match on (rule, path, source text), not line
  numbers, so unrelated edits don't invalidate them; regenerate with
  ``--write-baseline``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable, Iterator, Sequence

#: marker for "every rule suppressed on this line"
ALL_RULES = "*"

_SUPPRESS_RE = re.compile(
    r"#\s*dpcorr-lint:\s*ignore(?:\[([A-Za-z0-9_,\- ]+)\])?")

_BASELINE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding: a rule broken at a specific line of a file."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    code: str = ""  # the stripped source line (baseline match key)
    #: interprocedural findings carry the file:line hop chain that
    #: reaches the offending site (``--deep``; empty for local rules).
    chain: tuple = ()

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        for hop in self.chain:
            out += f"\n      via {hop}"
        return out


class Module:
    """One parsed source file as handed to every checker: the AST (with
    parent links), the raw lines, and the per-line suppression table."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # parent links let rules see an access site's enclosing context
        # (e.g. "is this Attribute the receiver of a mutating call");
        # single-stack traversal: one iter_child_nodes pass per node
        stack: list[ast.AST] = [self.tree]
        while stack:
            node = stack.pop()
            for child in ast.iter_child_nodes(node):
                child._dpcorr_parent = node  # type: ignore[attr-defined]
                stack.append(child)
        self.suppressions = _suppression_table(self.lines)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        for ln in (lineno, lineno - 1):
            rules = self.suppressions.get(ln)
            if rules is None:
                continue
            if ALL_RULES in rules or rule in rules:
                # a standalone comment suppresses the line below it; an
                # inline comment suppresses its own line only
                if ln == lineno or self.line_text(ln).startswith("#"):
                    return True
        return False


def _suppression_table(lines: Sequence[str]) -> dict[int, set[str]]:
    table: dict[int, set[str]] = {}
    for i, line in enumerate(lines, 1):
        if "dpcorr-lint" not in line:  # fast path: regex only on hits
            continue
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        names = m.group(1)
        if names is None:
            table[i] = {ALL_RULES}
        else:
            table[i] = {n.strip() for n in names.split(",") if n.strip()}
    return table


class Checker:
    """One checker family (a plugin). Subclasses declare their rules
    and implement :meth:`check`; :meth:`applies_to` scopes the family
    to the part of the tree where its invariant lives (path-segment
    based, so the test fixtures mirror the layout instead of needing a
    parallel configuration language)."""

    #: family name (``--rules`` selector)
    name: str = ""
    #: rule id → one-line description (``--list-rules``)
    rules: dict[str, str] = {}

    def applies_to(self, relpath: str) -> bool:
        return True

    def check(self, module: Module) -> Iterator[Violation]:
        raise NotImplementedError


class ProjectChecker(Checker):
    """A ``--deep`` checker: sees the whole parsed project at once (the
    interprocedural model from :mod:`dpcorr.analysis.callgraph`) instead
    of one module at a time. ``applies_to`` still scopes which findings
    survive (by the *finding's* path), so fixtures compose the same way
    as for per-module rules."""

    def check(self, module: Module) -> Iterator[Violation]:
        return iter(())

    def check_project(self, model) -> Iterator[Violation]:
        raise NotImplementedError


# -------------------------------------------------------- AST helpers ----
def attr_chain(node: ast.AST) -> tuple[str, ...]:
    """``self.coalescer.submit`` → ``("self", "coalescer", "submit")``;
    empty tuple when the expression is not a plain name/attribute path
    (calls, subscripts and literals all break the chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def call_chain(call: ast.Call) -> tuple[str, ...]:
    """The called name as a chain (``jax.random.fold_in`` →
    ``("jax", "random", "fold_in")``)."""
    return attr_chain(call.func)


def imported_names(tree: ast.Module) -> dict[str, str]:
    """Name → dotted origin for every import binding in the module
    (``import numpy as np`` → ``{"np": "numpy"}``; ``from jax.random
    import fold_in`` → ``{"fold_in": "jax.random.fold_in"}``). Rules
    use this to tell stdlib ``random`` from ``jax.random`` and to spot
    re-exported draw wrappers. Cached on the tree: several rule
    families ask for the same module's imports."""
    cached = getattr(tree, "_dpcorr_imports", None)
    if cached is not None:
        return cached
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                out[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    tree._dpcorr_imports = out  # type: ignore[attr-defined]
    return out


def parent(node: ast.AST) -> ast.AST | None:
    return getattr(node, "_dpcorr_parent", None)


def walk_all(tree: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk``, memoized on the root node. Nearly every rule
    family sweeps the full module tree at least once; Module keeps the
    trees alive, so the first sweep pays for all of them."""
    cached = getattr(tree, "_dpcorr_all", None)
    if cached is None:
        cached = list(ast.walk(tree))
        try:
            tree._dpcorr_all = cached  # type: ignore[attr-defined]
        except AttributeError:
            pass
    return iter(cached)


def walk_same_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Like ``ast.walk`` but does not descend into nested function
    scopes (defs/lambdas) — the unit most rules reason over. The root
    node itself is yielded (and descended into) even when it is a
    function. Memoized on the root node: every rule family walks the
    same function scopes, and the trees outlive the walk (Module holds
    them), so one traversal serves all checkers."""
    cached = getattr(node, "_dpcorr_scope", None)
    if cached is None:
        cached = [node]
        stack = [node]
        while stack:
            for child in ast.iter_child_nodes(stack.pop()):
                cached.append(child)
                if not isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                    stack.append(child)
        try:
            node._dpcorr_scope = cached  # type: ignore[attr-defined]
        except AttributeError:
            pass
    return iter(cached)


# ------------------------------------------------------------ running ----
def iter_py_files(paths: Iterable[str], root: str) -> Iterator[str]:
    """Yield root-relative paths of every ``.py`` under ``paths``
    (files or directories), skipping caches and hidden directories."""
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            yield os.path.relpath(full, root)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if not d.startswith(".")
                                 and d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.relpath(os.path.join(dirpath, fn), root)


def default_checkers(deep: bool = False) -> list[Checker]:
    """The shipped checker families (imported lazily so ``core`` has no
    import cycle with the rule modules). ``deep`` adds the
    interprocedural families (``--deep``)."""
    from dpcorr.analysis.rules import ALL_CHECKERS, DEEP_CHECKERS

    out = [cls() for cls in ALL_CHECKERS]
    if deep:
        out.extend(cls() for cls in DEEP_CHECKERS)
    return out


def run_lint(paths: Sequence[str], root: str,
             checkers: Sequence[Checker] | None = None,
             rule_filter: Sequence[str] | None = None,
             deep: bool = False) -> list[Violation]:
    """Lint every ``.py`` under ``paths`` (relative to ``root``) and
    return suppression-filtered violations in (path, line) order.
    ``rule_filter`` restricts to the named checker families. ``deep``
    additionally builds the interprocedural model over every parsed
    module and runs the :class:`ProjectChecker` families on it."""
    if checkers is None:
        checkers = default_checkers(deep=deep)
    if rule_filter:
        wanted = set(rule_filter)
        unknown = wanted - {c.name for c in checkers}
        if unknown:
            raise ValueError(f"unknown checker families: {sorted(unknown)}")
        checkers = [c for c in checkers if c.name in wanted]
    violations: list[Violation] = []
    modules: list[Module] = []
    for relpath in iter_py_files(paths, root):
        full = os.path.join(root, relpath)
        with open(full, encoding="utf-8") as f:
            source = f.read()
        try:
            module = Module(full, relpath, source)
        except SyntaxError as e:
            violations.append(Violation(
                "syntax-error", relpath.replace(os.sep, "/"),
                e.lineno or 1, f"cannot parse: {e.msg}"))
            continue
        modules.append(module)
        for checker in checkers:
            if isinstance(checker, ProjectChecker):
                continue
            if not checker.applies_to(module.relpath):
                continue
            for v in checker.check(module):
                if not module.suppressed(v.rule, v.line):
                    violations.append(dataclasses.replace(
                        v, code=module.line_text(v.line)))
    if deep:
        from dpcorr.analysis.callgraph import ProjectModel

        model = ProjectModel(modules, root)
        by_relpath = {m.relpath: m for m in modules}
        for checker in checkers:
            if not isinstance(checker, ProjectChecker):
                continue
            for v in checker.check_project(model):
                if not checker.applies_to(v.path):
                    continue
                mod = by_relpath.get(v.path)
                if mod is not None and mod.suppressed(v.rule, v.line):
                    continue
                code = mod.line_text(v.line) if mod is not None else ""
                violations.append(dataclasses.replace(v, code=code))
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations


# ----------------------------------------------------------- baseline ----
def load_baseline(path: str) -> list[dict]:
    with open(path, encoding="utf-8") as f:
        state = json.load(f)
    if state.get("version") != _BASELINE_VERSION:
        raise ValueError(f"baseline {path!r} has version "
                         f"{state.get('version')!r}, "
                         f"expected {_BASELINE_VERSION}")
    return list(state["entries"])


def write_baseline(violations: Sequence[Violation], path: str) -> None:
    """Persist the current findings as the grandfathered set. Sorted
    and line-stamped for reviewability; matching ignores the line."""
    entries = [{"rule": v.rule, "path": v.path, "line": v.line,
                "code": v.code, "message": v.message}
               for v in violations]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": _BASELINE_VERSION, "entries": entries},
                  f, indent=1, sort_keys=True)
        f.write("\n")


def apply_baseline(violations: Sequence[Violation],
                   entries: Iterable[dict],
                   ) -> tuple[list[Violation], int, list[dict]]:
    """Split findings into (new, matched-count, stale-entries).

    An entry absorbs at most one finding with the same (rule, path,
    source text) — multiplicity is preserved, line numbers are not
    compared (pure moves must not resurrect triaged findings). Stale
    entries (nothing matched them — the violation was fixed) are
    reported so the baseline can be re-tightened with
    ``--write-baseline``; they never fail the gate.
    """
    budget: dict[tuple[str, str, str], int] = {}
    for e in entries:
        budget[(e["rule"], e["path"], e["code"])] = \
            budget.get((e["rule"], e["path"], e["code"]), 0) + 1
    new: list[Violation] = []
    matched = 0
    for v in violations:
        key = (v.rule, v.path, v.code)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            matched += 1
        else:
            new.append(v)
    stale = [{"rule": r, "path": p, "code": c, "count": n}
             for (r, p, c), n in sorted(budget.items()) if n > 0]
    return new, matched, stale
