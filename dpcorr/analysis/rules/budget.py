"""Budget discipline: charge-before-noise, refund-on-refusal
(serve/, protocol/ and stream/).

The serving layer's privacy invariant (serve.server module docstring)
is structural: the ledger must be charged — and durably persisted —
*before* a request can reach any noise-drawing execution path, and any
post-charge refusal (queue backpressure, closed coalescer) must reverse
the charge so shed load cannot drain budgets. The protocol layer has
the same invariant with the wire in place of the execution engine: a
release may be handed to the transport (``channel.send``) only after
``ledger.charge``, and a transport failure must refund — that is
exactly ``protocol.gate.ReleaseGate``, and these rules keep it the
*only* shape that lints. The stream layer repeats it once more with
the window releaser in place of the wire: a closable window reaches
``releaser.release`` only after its one atomic per-window charge, and
an in-process release failure must refund
(``stream.service.StreamService._release_window_locked``). Two rules, scoped
to functions that *hold a ledger*
(reference ``ledger``/``self.ledger``) — the admission layer —
because below the admission boundary (the coalescer, the kernel cache,
a channel handed in by the gate) requests are charged by contract:

- ``budget-uncharged-noise`` — an admission-layer function launches
  work (``coalescer.submit`` / ``cache.run_batch`` / ``channel.send``)
  with no ``ledger.charge``/``charge_request`` earlier in the
  function: a query could execute — or a release cross the wire —
  without its spend on disk.
- ``budget-missing-refund`` — the launch is not wrapped in a ``try``
  whose handler reaches ``ledger.refund``: an enqueue refusal (or a
  transport failure) after a successful charge would consume ε for a
  query that was never answered.
- ``budget-shed-missing-refund`` — a function settles a future with a
  *refusal* exception (``...set_exception(ServerOverloadedError(...))``
  and friends) without any ``*refund*`` call in the same function.
  Post-admission sheds — deadline expiry, priority eviction,
  close-drain — happen *below* the ledger (the coalescer refunds via a
  helper handed the charges at submit), so this rule keys on the call
  *name* rather than a ledger receiver: every shed site must at least
  route through something named refund. ISSUE 8 added three such sites
  at once; this is the shape that keeps the next one honest.
- ``budget-multi-charge-missing-refund`` — a function charges two
  *distinct* budget receivers (``ledger`` and the per-user
  ``directory``, serve.budget_dir) and any charge after the first
  receiver's is not inside a ``try`` whose handler reaches a refund:
  a refusal from the second store would leave the first one charged —
  the exact partial-spend the CompositeLedger's compensation path
  exists to prevent. The directory is itself a budget receiver for
  every rule here: ``directory.charge`` dominates an enqueue the same
  way ``ledger.charge`` does.
"""

from __future__ import annotations

import ast
from typing import Iterator

from dpcorr.analysis.core import (
    Checker,
    Module,
    Violation,
    attr_chain,
    walk_all,
    walk_same_scope,
)

#: method names that hand an admitted request to the execution layer —
#: in protocol/, a release to the transport; in stream/, a charged
#: window to the releaser.
ENQUEUE_FNS = frozenset({"submit", "run_batch", "send", "send_release",
                         "release"})
#: receivers those methods count on (any element of the access chain).
ENQUEUE_RECEIVERS = frozenset({"coalescer", "cache", "channel",
                               "transport", "releaser"})

CHARGE_FNS = frozenset({"charge", "charge_request"})
REFUND_FNS = frozenset({"refund"})
#: budget receivers: the per-party ledger and the per-user budget
#: directory (serve.budget_dir) are both charge/refund sinks.
LEDGER_NAMES = frozenset({"ledger", "directory"})

#: exception classes that refuse an ALREADY-ADMITTED (hence charged)
#: request — settling a future with one of these is a shed site.
REFUSAL_EXCS = frozenset({"ServerOverloadedError", "ServerClosedError",
                          "DeadlineExpiredError", "CircuitOpenError"})


def _is_ledger_call(call: ast.Call, fns: frozenset[str]) -> bool:
    chain = attr_chain(call.func)
    return (len(chain) >= 2 and chain[-1] in fns
            and any(part in LEDGER_NAMES for part in chain[:-1]))


def _is_enqueue_call(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return (len(chain) >= 2 and chain[-1] in ENQUEUE_FNS
            and any(part in ENQUEUE_RECEIVERS for part in chain[:-1]))


class BudgetChecker(Checker):
    name = "budget"
    rules = {
        "budget-uncharged-noise": "execution launched without a "
                                  "dominating ledger.charge in the "
                                  "admission layer",
        "budget-missing-refund": "post-charge enqueue not guarded by a "
                                 "refund-on-failure handler",
        "budget-shed-missing-refund": "future settled with a refusal "
                                      "exception in a function with no "
                                      "refund call",
        "budget-multi-charge-missing-refund": "charges two budget "
                                              "receivers without a "
                                              "compensating refund "
                                              "handler on the later "
                                              "charge",
    }

    def applies_to(self, relpath: str) -> bool:
        parts = relpath.split("/")
        return ("serve" in parts or "protocol" in parts
                or "stream" in parts)

    def check(self, module: Module) -> Iterator[Violation]:
        for fn in walk_all(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_shed_sites(module, fn)
            if not self._holds_ledger(fn):
                continue
            yield from self._check_fn(module, fn)

    def _check_shed_sites(self, module: Module, fn) -> Iterator[Violation]:
        """``budget-shed-missing-refund``: shed sites live below the
        admission layer (no ledger in scope), so the evidence of a
        refund is a call whose *name* contains ``refund`` — the
        coalescer's ``self._refund(...)`` helper, or ``ledger.refund``
        itself at admission sites."""
        sheds = [node for node in walk_same_scope(fn)
                 if isinstance(node, ast.Call)
                 and self._is_refusal_set_exception(node)]
        if not sheds:
            return
        if any(isinstance(node, ast.Call)
               and any("refund" in part
                       for part in attr_chain(node.func))
               for node in walk_same_scope(fn)):
            return
        for node in sheds:
            exc = attr_chain(node.args[0].func)[-1]
            yield Violation(
                "budget-shed-missing-refund", module.relpath, node.lineno,
                f"set_exception({exc}(...)) sheds an admitted request "
                f"but no refund call appears in this function — its "
                f"charge would be consumed for a query never answered")

    @staticmethod
    def _is_refusal_set_exception(call: ast.Call) -> bool:
        chain = attr_chain(call.func)
        if not chain or chain[-1] != "set_exception" or not call.args:
            return False
        arg = call.args[0]
        if not isinstance(arg, ast.Call):
            return False
        exc_chain = attr_chain(arg.func)
        return bool(exc_chain) and exc_chain[-1] in REFUSAL_EXCS

    @staticmethod
    def _holds_ledger(fn) -> bool:
        """Admission-layer test: the function itself references a
        ledger (``self.ledger`` / a local named ``ledger``)."""
        for node in walk_same_scope(fn):
            chain = ()
            if isinstance(node, ast.Attribute):
                chain = attr_chain(node)
            elif isinstance(node, ast.Name):
                chain = (node.id,)
            if any(part in LEDGER_NAMES for part in chain):
                return True
        return False

    @staticmethod
    def _charge_receiver(call: ast.Call) -> str:
        """Which budget receiver a charge call hits (``ledger`` /
        ``directory``) — the first chain part that names one."""
        for part in attr_chain(call.func):
            if part in LEDGER_NAMES:
                return part
        return "?"

    def _check_multi_charge(self, module: Module, fn,
                            charges: list[ast.Call]) -> Iterator[Violation]:
        """``budget-multi-charge-missing-refund``: once a function has
        charged one receiver, every charge against a *different*
        receiver is a partial-spend hazard — a refusal there must be
        able to compensate the first store, so the later charge has to
        sit in a ``try`` whose handler reaches a refund."""
        if len({self._charge_receiver(c) for c in charges}) < 2:
            return
        first = min(charges, key=lambda c: c.lineno)
        for call in charges:
            if self._charge_receiver(call) == \
                    self._charge_receiver(first):
                continue
            if not self._refund_guarded(fn, call):
                yield Violation(
                    "budget-multi-charge-missing-refund", module.relpath,
                    call.lineno,
                    f"{'.'.join(attr_chain(call.func))} charges a "
                    f"second budget receiver after "
                    f"{self._charge_receiver(first)} was charged — a "
                    f"refusal here would leave the first store spent; "
                    f"wrap it in a try whose handler refunds the "
                    f"applied legs")

    def _check_fn(self, module: Module, fn) -> Iterator[Violation]:
        charge_calls = []
        for node in walk_same_scope(fn):
            if isinstance(node, ast.Call) and _is_ledger_call(node,
                                                              CHARGE_FNS):
                charge_calls.append(node)
        yield from self._check_multi_charge(module, fn, charge_calls)
        charge_lines = [c.lineno for c in charge_calls]
        first_charge = min(charge_lines) if charge_lines else None
        for node in walk_same_scope(fn):
            if not (isinstance(node, ast.Call) and _is_enqueue_call(node)):
                continue
            if first_charge is None or node.lineno < first_charge:
                yield Violation(
                    "budget-uncharged-noise", module.relpath, node.lineno,
                    f"{'.'.join(attr_chain(node.func))} launches "
                    f"execution but no ledger.charge dominates it in "
                    f"this admission-layer function")
                continue
            if not self._refund_guarded(fn, node):
                yield Violation(
                    "budget-missing-refund", module.relpath, node.lineno,
                    f"{'.'.join(attr_chain(node.func))} can refuse "
                    f"after the ledger was charged — wrap it in a try "
                    f"whose handler calls ledger.refund")

    @staticmethod
    def _refund_guarded(fn, enqueue: ast.Call) -> bool:
        """True when some ``try`` lexically containing the enqueue has
        a handler that reaches ``ledger.refund``."""
        for node in walk_same_scope(fn):
            if not isinstance(node, ast.Try):
                continue
            in_body = any(sub is enqueue for stmt in node.body
                          for sub in ast.walk(stmt))
            if not in_body:
                continue
            for handler in node.handlers:
                for stmt in handler.body:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call) and \
                                _is_ledger_call(sub, REFUND_FNS):
                            return True
        return False
