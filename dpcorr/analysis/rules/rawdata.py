"""No raw columns on the wire: taint from column names to serializers.

The protocol layer's core privacy claim (docs/PROTOCOL.md) is that only
DP *releases* ever reach a serializer — the raw x/y columns stay inside
their party process. The runtime proof is the transcript scan
(protocol.scan); this rule is the static half: inside ``protocol/``,
flag any ``encode_array``/``canonical_encode`` call whose payload
argument is *tainted* by a raw-column name.

Taint seeds are names that, by repo convention, hold raw sample data
(``x``, ``y``, ``col``, ``column``, ``raw_x`` …, and any attribute
ending in one of those, e.g. ``self.column``). Taint propagates
through plain aliasing — assignment, subscripts/slices of a tainted
value, and value-preserving passthroughs (``np.asarray``, ``astype``,
``sign``, ``clip``, ``reshape`` …: a sign or clip image of a column is
still that column's data). It deliberately does **not** propagate
through arithmetic (``BinOp``) or reductions: adding calibrated noise
or aggregating to batch means is exactly what turns a column into a
release, and flagging those would make every legitimate release a
finding.

One rule:

- ``raw-column-serialize`` — a wire serializer receives data reachable
  from a raw column by aliasing alone: that payload would put sample
  values on the socket verbatim.
"""

from __future__ import annotations

import ast
from typing import Iterator

from dpcorr.analysis.core import Checker, Module, Violation, \
    attr_chain, walk_all

#: names that hold raw sample data by repo convention.
RAW_NAMES = frozenset({
    "x", "y", "xs", "ys", "col", "column", "raw", "raw_x", "raw_y",
    "x_raw", "y_raw", "x_col", "y_col",
})

#: callables that return their input's values (possibly re-typed or
#: re-shaped) — aliasing, not anonymization.
PASSTHROUGH_FNS = frozenset({
    "asarray", "array", "ascontiguousarray", "astype", "clip",
    "clip_sym", "copy", "ravel", "reshape", "sign", "tolist", "float32",
})

#: the wire boundary: anything handed to these may leave the process.
SERIALIZE_FNS = frozenset({"encode_array", "canonical_encode"})


def _is_raw_name(node: ast.AST, tainted: set[str]) -> bool:
    chain = attr_chain(node)
    if not chain:
        return False
    if chain[-1] in RAW_NAMES:
        return True
    return len(chain) == 1 and chain[0] in tainted


class RawDataChecker(Checker):
    name = "rawdata"
    rules = {
        "raw-column-serialize": "a wire serializer receives data "
                                "aliased from a raw column (no noise "
                                "between the sample and the socket)",
    }

    def applies_to(self, relpath: str) -> bool:
        return "protocol" in relpath.split("/")

    def check(self, module: Module) -> Iterator[Violation]:
        for fn in walk_all(module.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_fn(module, fn)

    def _check_fn(self, module: Module, fn) -> Iterator[Violation]:
        # one forward pass in source order: straight-line taint is all
        # the rule needs (protocol code builds payloads linearly), and
        # order-sensitivity keeps `col = noise(col)` rebindings honest.
        tainted: set[str] = set()
        sites = sorted(
            (node for node in ast.walk(fn)
             if isinstance(node, (ast.Assign, ast.Call))),
            key=lambda n: (n.lineno, n.col_offset))
        for node in sites:
            if isinstance(node, ast.Assign):
                if self._tainted_expr(node.value, tainted):
                    for tgt in node.targets:
                        for name in ast.walk(tgt):
                            if isinstance(name, ast.Name):
                                tainted.add(name.id)
                else:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            tainted.discard(tgt.id)
                continue
            chain = attr_chain(node.func)
            if chain and chain[-1] in SERIALIZE_FNS and node.args:
                if self._tainted_expr(node.args[0], tainted):
                    yield Violation(
                        "raw-column-serialize", module.relpath,
                        node.lineno,
                        f"{'.'.join(chain)} receives a value aliased "
                        f"from a raw column — only DP releases may be "
                        f"serialized for the wire")

    def _tainted_expr(self, node: ast.AST, tainted: set[str]) -> bool:
        if isinstance(node, (ast.Name, ast.Attribute)):
            return _is_raw_name(node, tainted)
        if isinstance(node, ast.Subscript):
            return self._tainted_expr(node.value, tainted)
        if isinstance(node, ast.Starred):
            return self._tainted_expr(node.value, tainted)
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if not chain:
                return False
            if chain[-1] in PASSTHROUGH_FNS:
                # np.sign(col) / col.astype(...): receiver or any
                # argument carries the taint through
                if len(chain) > 1 and _is_raw_name(
                        node.func.value, tainted):
                    return True
                return any(self._tainted_expr(a, tainted)
                           for a in node.args)
            return False
        # BinOp / reductions / comprehensions: anonymizing by intent
        return False
