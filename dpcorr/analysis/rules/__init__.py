"""The shipped checker families (docs/STATIC_ANALYSIS.md).

Each module is one plugin: a :class:`dpcorr.analysis.core.Checker`
subclass declaring its rule ids and the slice of the tree it applies
to. Adding a family = adding a module here and listing its class in
``ALL_CHECKERS`` — the runner, CLI, baseline and ``--list-rules`` all
derive from this list.
"""

from dpcorr.analysis.rules.budget import BudgetChecker
from dpcorr.analysis.rules.compilepath import CompilePathChecker
from dpcorr.analysis.rules.coverage import ChaosCoverageChecker
from dpcorr.analysis.rules.deepbudget import DeepBudgetChecker
from dpcorr.analysis.rules.durability import DurabilityChecker
from dpcorr.analysis.rules.lockorder import LockOrderChecker
from dpcorr.analysis.rules.locks import LockChecker
from dpcorr.analysis.rules.metrics import MetricsChecker
from dpcorr.analysis.rules.purity import PurityChecker
from dpcorr.analysis.rules.rawdata import RawDataChecker
from dpcorr.analysis.rules.rng import RngChecker
from dpcorr.analysis.rules.sync import SyncChecker

#: registration order is report order for equal (path, line).
ALL_CHECKERS = (RngChecker, BudgetChecker, LockChecker, PurityChecker,
                RawDataChecker, SyncChecker, MetricsChecker,
                CompilePathChecker)

#: the interprocedural (``--deep``) families — ProjectChecker
#: subclasses run over the callgraph model after the per-module pass.
DEEP_CHECKERS = (LockOrderChecker, DurabilityChecker, DeepBudgetChecker,
                 ChaosCoverageChecker)
