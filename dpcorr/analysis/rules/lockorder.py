"""Lock-order and blocking-under-lock analysis (``--deep``).

Built on the interprocedural model (analysis/callgraph.py): every
``threading.Lock``/``RLock`` created in the tree is a node, and every
place the code acquires lock B while lexically — or through a resolved
call chain — holding lock A is an edge A→B. Two rules:

- ``lock-order-cycle`` — the edge graph has a cycle: two threads
  taking the component's locks from different entry points can
  deadlock. The finding names every edge of the cycle with the full
  file:line acquisition chain for each direction, so the report shows
  *both* paths (the pair of stacks a deadlock debugger would show you,
  computed before the deadlock exists). A self-edge on a plain
  ``Lock`` (re-acquiring a non-reentrant lock you already hold) is
  reported as a 1-cycle: that one is not a race, it is a guaranteed
  hang.
- ``blocking-under-lock`` — a call that can block on the outside world
  (``fsync``, ``subprocess``, socket I/O, ``Future.result()``,
  ``Thread.join()``, ``time.sleep``, ``Event.wait``) is reachable
  while a lock is held. Holding a lock across I/O turns one slow disk
  into a stalled lock convoy. The repo's WAL-before-ack design *does*
  fsync under the admission locks on purpose — those sites carry a
  ``# dpcorr-lint: ignore[blocking-under-lock]`` with a justification,
  which is exactly the reviewable escape hatch this rule exists to
  force.

Findings are anchored at the outermost frame that holds the lock (the
acquisition or call site in the holder), with the rest of the path in
the chain — so a suppression sits next to the lock that makes the
blocking call a decision, not next to the innocent helper.
"""

from __future__ import annotations

from typing import Iterator

from dpcorr.analysis.callgraph import BLOCKING_KINDS, ProjectModel
from dpcorr.analysis.core import ProjectChecker, Violation

#: blocking effect kinds worth flagging under a lock (a subset of the
#: model's effect kinds — ``replace``/``sweep``/``quarantine`` are fast
#: metadata ops and are durability-rule business, not convoy risks).
_FLAGGED = frozenset(BLOCKING_KINDS)


def _site_line(site: str) -> tuple[str, int]:
    """``"dpcorr/serve/ledger.py:162 (PrivacyLedger.charge)"`` →
    (path, 162)."""
    head = site.split(" ", 1)[0]
    path, _, line = head.rpartition(":")
    return path, int(line)


class LockOrderChecker(ProjectChecker):
    name = "lockorder"
    rules = {
        "lock-order-cycle": "two acquisition paths take the same locks "
                            "in opposite orders (potential deadlock)",
        "blocking-under-lock": "fsync/subprocess/socket/result()/join() "
                               "reachable while a lock is held",
    }

    def check_project(self, model: ProjectModel) -> Iterator[Violation]:
        yield from self._cycles(model)
        yield from self._blocking(model)

    # ------------------------------------------------------- cycles ----
    def _cycles(self, model: ProjectModel) -> Iterator[Violation]:
        for cycle in model.lock_cycles():
            a, b, chain = cycle[0]
            path, line = _site_line(chain[0])
            if len(cycle) == 1:
                yield Violation(
                    "lock-order-cycle", path, line,
                    f"re-acquires non-reentrant lock {a} while already "
                    f"holding it — this path self-deadlocks",
                    chain=tuple(chain))
            else:
                locks = " -> ".join([e[0] for e in cycle] + [a])
                full_chain: list[str] = []
                for (ea, eb, ec) in cycle:
                    full_chain.append(f"[{ea} -> {eb}]")
                    full_chain.extend(ec)
                yield Violation(
                    "lock-order-cycle", path, line,
                    f"lock-order cycle {locks}: each bracketed path "
                    f"below acquires the second lock while holding the "
                    f"first — two threads entering from different "
                    f"edges can deadlock",
                    chain=tuple(full_chain))

    # ------------------------------------------------ blocking calls ----
    def _blocking(self, model: ProjectModel) -> Iterator[Violation]:
        seen: set[tuple[str, int, str]] = set()
        for fi in model.functions.values():
            for eff in fi.effects:
                if eff.kind in _FLAGGED and eff.held:
                    key = (fi.relpath, eff.lineno, eff.kind)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Violation(
                        "blocking-under-lock", fi.relpath, eff.lineno,
                        f"{eff.text} ({eff.kind}) blocks while holding "
                        f"{', '.join(eff.held)}",
                        chain=(fi.site(eff.lineno),))
            for cs in fi.calls:
                if not cs.held or cs.target is None:
                    continue
                effects = model.transitive_effects(cs.target)
                for kind in sorted(_FLAGGED & set(effects)):
                    key = (fi.relpath, cs.lineno, kind)
                    if key in seen:
                        continue
                    seen.add(key)
                    chain = (fi.site(cs.lineno),) + effects[kind]
                    yield Violation(
                        "blocking-under-lock", fi.relpath, cs.lineno,
                        f"{cs.text} reaches a {kind} call while "
                        f"holding {', '.join(cs.held)}",
                        chain=chain)
