"""RNG hygiene: the named-stream key-tree discipline (utils.rng).

The framework's determinism *and* privacy contract is the key-tree
``master → design point → replication → named substream``: every noise
draw has a collision-resistant address and no PRNG key is ever consumed
twice (Mironov-style attacks start exactly at reused or ad-hoc keys —
PAPERS.md, ISSUE 3). Three rules:

- ``rng-key-reuse`` — one key variable fed to two draw calls in the
  same function without an intervening ``split``/reassignment: the two
  draws are perfectly correlated, which voids the DP noise analysis
  (and silently biases even non-private statistics).
- ``rng-literal-seed`` — a literal integer seeding a key constructor in
  library code: seeds must flow from configuration (``SimConfig.seed``,
  ``--seed``) so runs are reproducible *and* re-seedable; a buried
  constant is neither.
- ``rng-raw-api`` — ``jax.random.key``/``PRNGKey``/raw ``fold_in``
  outside ``utils/rng.py``: key construction and stream addressing go
  through the named-stream API (``rng.master_key``/``stream``/
  ``design_key``/``rep_keys``) so stream addresses stay stable across
  code movement and auditable in one place.
"""

from __future__ import annotations

import ast
from typing import Iterator

from dpcorr.analysis.core import (
    Checker,
    Module,
    Violation,
    call_chain,
    imported_names,
    walk_all,
    walk_same_scope,
)

#: jax.random sampling endpoints that *consume* a key (draw from it).
DRAW_FNS = frozenset({
    "ball", "bernoulli", "beta", "binomial", "bits", "categorical",
    "cauchy", "chisquare", "choice", "dirichlet", "double_sided_maxwell",
    "exponential", "f", "gamma", "geometric", "gumbel", "laplace",
    "loggamma", "logistic", "lognormal", "maxwell", "multivariate_normal",
    "normal", "orthogonal", "pareto", "permutation", "poisson", "rademacher",
    "randint", "rayleigh", "t", "triangular", "truncated_normal", "uniform",
    "wald", "weibull_min",
})

#: key-deriving endpoints — using these repeatedly on one key is the
#: sanctioned pattern, not reuse.
DERIVE_FNS = frozenset({"split", "fold_in", "clone", "wrap_key_data"})

#: repo-local draw wrappers (dotted origins) that consume their first
#: argument exactly like a jax.random draw does.
WRAPPER_DRAW_ORIGINS = frozenset({
    "dpcorr.ops.noise.laplace",
})

#: named-stream derivation helpers (dotted origins): feeding one key to
#: several of these is addressing, not consumption.
STREAM_API_ORIGINS = frozenset({
    "dpcorr.utils.rng.stream",
    "dpcorr.utils.rng.design_key",
    "dpcorr.utils.rng.chunk_key",
    "dpcorr.utils.rng.rep_keys",
    "dpcorr.utils.rng.pallas_seeds",
})

#: key constructors a literal seed must not reach.
SEED_CTORS = frozenset({"key", "PRNGKey", "master_key"})


def _is_rng_file(relpath: str) -> bool:
    return relpath.endswith("utils/rng.py")


class RngChecker(Checker):
    name = "rng"
    rules = {
        "rng-key-reuse": "a PRNG key fed to two draws without an "
                         "intervening split/reassignment",
        "rng-literal-seed": "literal integer seed reaching a key "
                            "constructor in library code",
        "rng-raw-api": "jax.random.key/PRNGKey/fold_in outside "
                       "utils/rng.py (use the named-stream API)",
    }

    def check(self, module: Module) -> Iterator[Violation]:
        imports = imported_names(module.tree)
        yield from self._raw_api(module)
        yield from self._literal_seeds(module)
        for fn in walk_all(module.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
                yield from self._key_reuse(module, fn, imports)

    # ---------------------------------------------------- rng-raw-api ----
    def _raw_api(self, module: Module) -> Iterator[Violation]:
        if _is_rng_file(module.relpath):
            return
        imports = imported_names(module.tree)
        for node in walk_all(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = call_chain(node)
            if not chain:
                continue
            origin = self._origin(chain, imports)
            if origin in ("jax.random.fold_in", "jax.random.key",
                          "jax.random.PRNGKey"):
                api = origin.rsplit(".", 1)[1]
                fix = ("rng.design_key / rng.stream"
                       if api == "fold_in" else "rng.master_key")
                yield Violation(
                    "rng-raw-api", module.relpath, node.lineno,
                    f"raw jax.random.{api} outside utils/rng.py — "
                    f"use the named-stream API ({fix})")

    # ----------------------------------------------- rng-literal-seed ----
    def _literal_seeds(self, module: Module) -> Iterator[Violation]:
        if _is_rng_file(module.relpath):
            return
        imports = imported_names(module.tree)
        for node in walk_all(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = call_chain(node)
            if not chain or chain[-1] not in SEED_CTORS:
                continue
            origin = self._origin(chain, imports)
            if origin not in ("jax.random.key", "jax.random.PRNGKey",
                              "dpcorr.utils.rng.master_key"):
                continue
            seed = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "seed":
                    seed = kw.value
            if isinstance(seed, ast.Constant) and isinstance(seed.value,
                                                             int):
                yield Violation(
                    "rng-literal-seed", module.relpath, node.lineno,
                    f"literal seed {seed.value} passed to "
                    f"{chain[-1]} — thread the seed from configuration")

    # -------------------------------------------------- rng-key-reuse ----
    def _key_reuse(self, module: Module, fn, imports: dict[str, str],
                   ) -> Iterator[Violation]:
        """Structured linear scan over one function scope: a bare-name
        key consumed by a second draw without an intervening rebind is
        a violation. Branches of an ``if`` are scanned independently
        (exclusive paths may each draw once) and merged; loop bodies
        are scanned once (a key reused *across* iterations is invisible
        statically — the named-stream API is the defense there)."""
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        violations: list[Violation] = []
        self._scan(body if isinstance(body, list) else [body],
                   set(), imports, violations, module)
        yield from violations

    def _scan(self, stmts, consumed: set[str], imports, out, module):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # nested scopes are scanned on their own
            if isinstance(stmt, ast.If):
                self._scan_expr(stmt.test, consumed, imports, out, module)
                a, b = set(consumed), set(consumed)
                self._scan(stmt.body, a, imports, out, module)
                self._scan(stmt.orelse, b, imports, out, module)
                # a branch that leaves the function contributes nothing
                # to the fall-through state (early-return guard draws
                # must not poison the main path)
                if not self._terminates(stmt.body):
                    consumed |= a
                if not self._terminates(stmt.orelse):
                    consumed |= b
                continue
            if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                test = stmt.iter if isinstance(
                    stmt, (ast.For, ast.AsyncFor)) else stmt.test
                self._scan_expr(test, consumed, imports, out, module)
                a = set(consumed)
                self._scan(stmt.body, a, imports, out, module)
                self._scan(stmt.orelse, a, imports, out, module)
                consumed |= a
                continue
            if isinstance(stmt, ast.Try):
                a = set(consumed)
                self._scan(stmt.body, a, imports, out, module)
                for h in stmt.handlers:
                    self._scan(h.body, set(a), imports, out, module)
                self._scan(stmt.orelse, a, imports, out, module)
                self._scan(stmt.finalbody, a, imports, out, module)
                consumed |= a
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._scan(stmt.body, consumed, imports, out, module)
                continue
            # expression statements / assignments: find draws in source
            # order, then apply rebinds
            self._scan_expr(stmt, consumed, imports, out, module)
            for target in self._bound_names(stmt):
                consumed.discard(target)

    def _scan_expr(self, node, consumed: set[str], imports, out, module):
        """Record draws in one expression/simple statement, without
        descending into nested function scopes."""
        if node is None:
            return
        for sub in walk_same_scope(node):
            if isinstance(sub, ast.Call):
                key = self._consumed_key(sub, imports)
                if key is not None:
                    if key in consumed:
                        out.append(Violation(
                            "rng-key-reuse", module.relpath, sub.lineno,
                            f"key {key!r} already consumed by an "
                            f"earlier draw in this function — split "
                            f"or derive a named stream first"))
                    else:
                        consumed.add(key)

    def _consumed_key(self, call: ast.Call, imports) -> str | None:
        """The bare variable name this call consumes as a PRNG key, or
        None when the call is not a draw / takes a derived key."""
        chain = call_chain(call)
        if not chain:
            return None
        tail = chain[-1]
        origin = self._origin(chain, imports)
        if origin in STREAM_API_ORIGINS:
            return None  # addressing, not consumption — never a draw
        is_draw = False
        if origin in WRAPPER_DRAW_ORIGINS:
            is_draw = True
        elif tail in DRAW_FNS and tail not in DERIVE_FNS:
            # qualify by resolved origin: only jax.random consumes keys
            # — stdlib `random` and `numpy.random` draws take no key
            # (they are the purity checker's problem), and a bare local
            # helper named `normal` is not a key consumer
            if origin.startswith("jax.random."):
                is_draw = True
        if not is_draw or not call.args:
            return None
        first = call.args[0]
        if isinstance(first, ast.Name):
            return first.id
        return None

    @staticmethod
    def _terminates(stmts) -> bool:
        """Does this block unconditionally leave the enclosing scope?"""
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))

    @staticmethod
    def _bound_names(stmt: ast.AST):
        """Names (re)bound by this statement — a rebind resets the
        consumed state of that name."""
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Store):
                yield node.id

    @staticmethod
    def _origin(chain: tuple[str, ...], imports: dict[str, str]) -> str:
        """Resolve a call chain to its dotted origin through the
        module's import bindings (``jr.fold_in`` with ``import
        jax.random as jr`` → ``jax.random.fold_in``)."""
        root = imports.get(chain[0], chain[0])
        return ".".join((root,) + chain[1:])
