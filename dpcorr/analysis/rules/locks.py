"""Lock discipline: ``# guarded by: <lock>`` declarations, enforced.

The threaded layers (serve coalescer/ledger/stats/kernel cache, obs
tracer/registry/audit trail) already follow a convention: shared
mutable state is documented as guarded by an instance lock and touched
only under ``with self.<lock>``. This rule makes the convention
checkable. Declare at the attribute's construction site::

    self._spent: dict[str, float] = {}  # guarded by: _lock

and every other access of ``self._spent`` inside the class must sit
lexically inside ``with self.<lock>:`` (a ``threading.Condition``
wrapping the lock counts — ``with self._cond`` acquires it). Two
rules, split so reads can be triaged separately from writes:

- ``lock-unguarded-write`` — assignment, ``del``, subscript store, or
  a mutating method call (``append``/``pop``/``update``/...) outside
  the guard: a torn write other threads can observe.
- ``lock-unguarded-read`` — a plain read outside the guard: may see a
  torn/stale value (Python's GIL makes many such reads *atomic* but
  not *coherent* with multi-step updates).

Exemptions, matching the repo's conventions: ``__init__`` (no
concurrency before construction completes) and methods named
``*_locked`` (documented caller-holds-the-lock helpers — the call
sites are checked instead, because the calls appear under the guard).
Nested functions defined under a guard are scanned as *unguarded*:
closures outlive the ``with`` block that created them.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from dpcorr.analysis.core import Checker, Module, Violation, parent, \
    walk_all

_DECL_RE = re.compile(r"#\s*guarded by:\s*(\w+)")

#: method names that mutate their receiver in place.
MUTATOR_FNS = frozenset({
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "move_to_end", "pop", "popitem", "popleft", "remove",
    "reverse", "rotate", "setdefault", "sort", "update",
    "write", "writelines", "close", "flush", "truncate",
})


class LockChecker(Checker):
    name = "locks"
    rules = {
        "lock-unguarded-write": "declared-guarded attribute mutated "
                                "outside `with self.<lock>`",
        "lock-unguarded-read": "declared-guarded attribute read "
                               "outside `with self.<lock>`",
    }

    def applies_to(self, relpath: str) -> bool:
        # every package under dpcorr/ (ISSUE 18 widened this from the
        # serve/obs/protocol subset: the stream service, chaos plans
        # and the compile cache all share state across threads too);
        # the bare segment names keep the test fixtures, which mirror
        # the layout without the leading dpcorr/, in scope
        parts = relpath.split("/")
        return ("dpcorr" in parts or "serve" in parts or "obs" in parts
                or "protocol" in parts or "stream" in parts
                or relpath.endswith("utils/compile.py"))

    def check(self, module: Module) -> Iterator[Violation]:
        classes = {cls.name: cls for cls in walk_all(module.tree)
                   if isinstance(cls, ast.ClassDef)}
        for cls in classes.values():
            yield from self._check_class(module, cls, classes)
        yield from self._check_module(module)

    # ------------------------------------------------- declarations ----
    def _declared(self, module: Module, cls: ast.ClassDef,
                  ) -> dict[str, str]:
        """attr → guard name, from ``self.X = ...  # guarded by: G``
        lines anywhere in the class body."""
        declared: dict[str, str] = {}
        for node in ast.walk(cls):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                m = _DECL_RE.search(module.line_text(node.lineno))
                if m:
                    declared[t.attr] = m.group(1)
        return declared

    # ------------------------------------------------------ checking ----
    def _check_class(self, module: Module, cls: ast.ClassDef,
                     classes: dict[str, ast.ClassDef],
                     ) -> Iterator[Violation]:
        # declarations are inherited: a subclass in the same module is
        # held to the guards its (lexically visible) bases declared
        declared: dict[str, str] = {}
        for c in self._mro_local(cls, classes):
            declared.update(self._declared(module, c))
        if not declared:
            return
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if item.name in ("__init__", "__post_init__") \
                    or item.name.endswith("_locked"):
                continue
            yield from self._scan(module, declared, item.body,
                                  held=frozenset())

    def _scan(self, module: Module, declared: dict[str, str],
              stmts, held: frozenset[str]) -> Iterator[Violation]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # closures escape the current guard (see module doc)
                yield from self._scan(module, declared, stmt.body,
                                      held=frozenset())
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                now = set(held)
                for it in stmt.items:
                    attr = self._self_attr(it.context_expr)
                    if attr:
                        now.add(attr)
                yield from self._scan(module, declared, stmt.body,
                                      frozenset(now))
                continue
            for field, value in ast.iter_fields(stmt):
                blocks = {"body", "orelse", "finalbody"}
                if field in blocks and isinstance(value, list):
                    yield from self._scan(module, declared, value, held)
                elif field == "handlers":
                    for h in value:
                        yield from self._scan(module, declared, h.body,
                                              held)
                else:
                    yield from self._scan_expr(module, declared,
                                               value, held)

    def _scan_expr(self, module: Module, declared, value,
                   held: frozenset[str]) -> Iterator[Violation]:
        nodes = value if isinstance(value, list) else [value]
        for node in nodes:
            if not isinstance(node, ast.AST):
                continue
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                        and sub.attr in declared):
                    continue
                guard = declared[sub.attr]
                if guard in held:
                    continue
                kind = self._access_kind(sub)
                yield Violation(
                    f"lock-unguarded-{kind}", module.relpath, sub.lineno,
                    f"self.{sub.attr} is declared `# guarded by: "
                    f"{guard}` but this {kind} is outside "
                    f"`with self.{guard}`")

    # --------------------------------------- module-level globals ----
    def _check_module(self, module: Module) -> Iterator[Violation]:
        """Module globals declared ``NAME = ...  # guarded by: _LOCK``
        (the chaos plan registry is the motivating case) are held to
        ``with <lock>:`` inside every module-level function. Import
        time is single-threaded, so top-level statements are exempt —
        like ``__init__`` for instance attributes."""
        declared: dict[str, str] = {}
        for node in module.tree.body:
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    m = _DECL_RE.search(module.line_text(node.lineno))
                    if m:
                        declared[t.id] = m.group(1)
        if not declared:
            return
        for item in module.tree.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and not item.name.endswith("_locked"):
                yield from self._scan_globals(module, declared,
                                              item.body, frozenset())

    def _scan_globals(self, module: Module, declared: dict[str, str],
                      stmts, held: frozenset[str],
                      ) -> Iterator[Violation]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan_globals(module, declared,
                                              stmt.body, frozenset())
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                now = set(held)
                for it in stmt.items:
                    if isinstance(it.context_expr, ast.Name):
                        now.add(it.context_expr.id)
                yield from self._scan_globals(module, declared,
                                              stmt.body, frozenset(now))
                continue
            for field, value in ast.iter_fields(stmt):
                blocks = {"body", "orelse", "finalbody"}
                if field in blocks and isinstance(value, list):
                    yield from self._scan_globals(module, declared,
                                                  value, held)
                elif field == "handlers":
                    for h in value:
                        yield from self._scan_globals(module, declared,
                                                      h.body, held)
                else:
                    yield from self._scan_global_expr(module, declared,
                                                      value, held)

    def _scan_global_expr(self, module: Module, declared, value,
                          held: frozenset[str]) -> Iterator[Violation]:
        nodes = value if isinstance(value, list) else [value]
        for node in nodes:
            if not isinstance(node, ast.AST):
                continue
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Name)
                        and sub.id in declared):
                    continue
                guard = declared[sub.id]
                if guard in held:
                    continue
                kind = self._name_access_kind(sub)
                yield Violation(
                    f"lock-unguarded-{kind}", module.relpath, sub.lineno,
                    f"module global {sub.id} is declared `# guarded "
                    f"by: {guard}` but this {kind} is outside "
                    f"`with {guard}`")

    @staticmethod
    def _name_access_kind(name_node: ast.Name) -> str:
        if isinstance(name_node.ctx, (ast.Store, ast.Del)):
            return "write"
        up = parent(name_node)
        if isinstance(up, ast.Subscript) \
                and isinstance(up.ctx, (ast.Store, ast.Del)):
            return "write"
        if isinstance(up, ast.AugAssign) and up.target is name_node:
            return "write"
        if isinstance(up, ast.Attribute) and up.attr in MUTATOR_FNS:
            call = parent(up)
            if isinstance(call, ast.Call) and call.func is up:
                return "write"
        return "read"

    @staticmethod
    def _mro_local(cls: ast.ClassDef,
                   classes: dict[str, ast.ClassDef]) -> list[ast.ClassDef]:
        """The class plus its same-module ancestors, bases first."""
        out, queue = [], [cls]
        while queue:
            c = queue.pop(0)
            if c in out:
                continue
            out.append(c)
            for base in c.bases:
                if isinstance(base, ast.Name) and base.id in classes:
                    queue.append(classes[base.id])
        return list(reversed(out))

    @staticmethod
    def _self_attr(expr) -> str | None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return expr.attr
        return None

    @staticmethod
    def _access_kind(attr_node: ast.Attribute) -> str:
        """'write' for stores/deletes/subscript-stores/mutator calls,
        'read' otherwise."""
        if isinstance(attr_node.ctx, (ast.Store, ast.Del)):
            return "write"
        up = parent(attr_node)
        # self.X[...] = / del self.X[...]
        if isinstance(up, ast.Subscript) \
                and isinstance(up.ctx, (ast.Store, ast.Del)):
            return "write"
        # self.X += ...
        if isinstance(up, ast.AugAssign) and up.target is attr_node:
            return "write"
        # self.X.append(...) and friends
        if isinstance(up, ast.Attribute) and up.attr in MUTATOR_FNS:
            call = parent(up)
            if isinstance(call, ast.Call) and call.func is up:
                return "write"
        return "read"
