"""Hot-path sync discipline: no per-iteration host syncs in rep loops.

The replication hot path (sim → grid dispatch → parallel backend →
bench) is fast *because* dispatch is asynchronous: blocks queue on the
device while the host prepares the next one, and the host blocks once,
at the reduction boundary (``sim.RepBlockPipeline.run``,
``dpcorr_transfer_fetches_total``). A ``block_until_ready``,
``np.asarray`` or ``jax.device_get`` inside a loop body silently turns
that pipeline back into lock-step round-trips — the accidental-sync
class the donated pipeline removed (r08), and exactly the regression
shape that produced the r03→r04 headline halving without any code
*looking* wrong. One rule:

- ``sync-in-loop`` — a host-synchronizing call (``block_until_ready``,
  ``numpy.asarray``/``numpy.array``, ``jax.device_get``) lexically
  inside a ``for``/``while`` body or a comprehension, in a hot-path
  module (sim, grid, parallel/, bench.py, benchmarks/).

Intentional boundaries — a completion barrier at the end of a fetch
phase, a drain loop that *measures* sync latency — carry an explicit
``# dpcorr-lint: ignore[sync-in-loop]`` so every deliberate sync site
is greppable and reviewed.
"""

from __future__ import annotations

import ast
from typing import Iterator

from dpcorr.analysis.core import (
    Checker,
    Module,
    Violation,
    call_chain,
    imported_names,
    walk_all,
)

#: call-chain tails that force a host sync regardless of origin
#: (method form ``x.block_until_ready()`` and ``jax.block_until_ready``)
SYNC_TAILS = frozenset({"block_until_ready"})

#: dotted origins that copy device values to host (and therefore block)
SYNC_ORIGINS = frozenset({
    "jax.device_get",
    "numpy.asarray",
    "numpy.array",
})

_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_COMPS = (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)


class SyncChecker(Checker):
    name = "sync"
    rules = {
        "sync-in-loop": "host sync (block_until_ready/np.asarray/"
                        "device_get) inside a rep-loop body — fetch once "
                        "at the reduction boundary",
    }

    def applies_to(self, relpath: str) -> bool:
        # the replication hot path only: these are the modules where a
        # per-iteration sync is a throughput bug rather than a style
        # choice (analysis code, tests and the serving layer fetch
        # values because they *need* them on host). The plan layer is
        # in scope since it became the shared dispatch/fetch boundary
        # (Executor.fetch is the one sanctioned sync — and it is not in
        # a loop).
        parts = relpath.split("/")
        return (relpath.endswith("sim.py") or relpath.endswith("grid.py")
                or "parallel" in parts or "plan" in parts
                or "benchmarks" in parts or parts[-1] == "bench.py")

    def check(self, module: Module) -> Iterator[Violation]:
        imports = imported_names(module.tree)
        seen: set[tuple[int, int]] = set()
        for node in walk_all(module.tree):
            if isinstance(node, _LOOPS):
                roots = node.body
            elif isinstance(node, ast.DictComp):
                roots = [node.key, node.value]
            elif isinstance(node, _COMPS):
                roots = [node.elt]
            else:
                continue
            for root in roots:
                yield from self._scan(module, root, imports, seen)

    def _scan(self, module: Module, root, imports, seen,
              ) -> Iterator[Violation]:
        """Yield sync calls under ``root``, skipping nested function
        scopes (a closure defined in a loop runs when *called*, and its
        own call sites are scanned wherever they sit) and deduplicating
        across nested loops."""
        stack = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            chain = call_chain(node)
            if not chain:
                continue
            origin = ".".join((imports.get(chain[0], chain[0]),)
                              + chain[1:])
            if chain[-1] not in SYNC_TAILS and origin not in SYNC_ORIGINS:
                continue
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue
            seen.add(key)
            yield Violation(
                "sync-in-loop", module.relpath, node.lineno,
                f"{'.'.join(chain)}(...) forces a host sync inside a "
                f"loop body — dispatch stays async until the reduction "
                f"boundary (one fetch per run, obs.transfer)")
