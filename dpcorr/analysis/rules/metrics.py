"""Telemetry discipline: metric naming and span lifecycle.

The obs layer (docs/OBSERVABILITY.md) works because every producer
speaks one dialect: series are ``dpcorr_``-prefixed snake_case (so a
dashboard can subscribe to ``dpcorr_*`` and get everything, and two
subsystems can't collide with an unprefixed ``requests_total``), and
every span that is opened is closed on all paths (a leaked span never
emits, so the request it covered simply vanishes from the trace — the
exact blind spot the flight recorder exists to remove). Two rules:

- ``metric-name-style`` — a Counter/Gauge/Histogram constructed outside
  ``dpcorr/obs/`` (direct constructor or ``registry.counter/gauge/
  histogram``) whose string-literal name is not ``dpcorr_`` + snake_case.
- ``span-no-finally`` — a ``tracer.start_span(...)`` whose span is not
  provably closed on all paths: the result must be bound to a name and
  that name's ``.end()`` must appear inside a ``finally`` block in the
  same scope (the ``with tracer.span(...)`` form is always fine and
  preferred).

Sites with a genuinely cross-scope lifecycle (a request root span ended
by the flush thread, a protocol session span ended in the session's own
finally) are baseline entries — reviewed once, greppable forever.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from dpcorr.analysis.core import (
    Checker,
    Module,
    Violation,
    call_chain,
    imported_names,
    walk_all,
)

#: what a series published through the shared registry must look like
METRIC_NAME_RE = re.compile(r"^dpcorr_[a-z0-9_]*$")

#: registry factory methods (Registry.counter/gauge/histogram)
FACTORY_TAILS = frozenset({"counter", "gauge", "histogram"})

#: direct-constructor origins (from dpcorr.obs.metrics import Counter)
CONSTRUCTOR_ORIGINS = frozenset({
    "dpcorr.obs.metrics.Counter",
    "dpcorr.obs.metrics.Gauge",
    "dpcorr.obs.metrics.Histogram",
    "dpcorr.obs.Counter",
    "dpcorr.obs.Gauge",
    "dpcorr.obs.Histogram",
})

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class MetricsChecker(Checker):
    name = "metrics"
    rules = {
        "metric-name-style": "metric name must be dpcorr_-prefixed "
                             "snake_case (docs/OBSERVABILITY.md — one "
                             "namespace for every producer)",
        "span-no-finally": "start_span(...) without a .end() in a "
                           "finally in the same scope — a leaked span "
                           "never emits; use `with tracer.span(...)` "
                           "or close in a finally",
    }

    def applies_to(self, relpath: str) -> bool:
        # everywhere EXCEPT the obs package itself: obs/ defines the
        # instruments (and its own tests exercise bad names on purpose
        # via fixtures, which live under tests/ and are out of scope)
        return "dpcorr/obs/" not in relpath and "dpcorr\\obs\\" not in relpath

    def check(self, module: Module) -> Iterator[Violation]:
        imports = imported_names(module.tree)
        yield from self._check_names(module, imports)
        yield from self._check_spans(module, imports)

    # -- metric-name-style ----------------------------------------------
    def _check_names(self, module: Module, imports,
                     ) -> Iterator[Violation]:
        for node in walk_all(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = call_chain(node)
            if not chain:
                continue
            origin = ".".join((imports.get(chain[0], chain[0]),)
                              + chain[1:])
            is_factory = (len(chain) >= 2 and chain[-1] in FACTORY_TAILS)
            is_ctor = origin in CONSTRUCTOR_ORIGINS
            if not (is_factory or is_ctor):
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue  # dynamic names are the registry's problem
            name = first.value
            if is_factory and not is_ctor and not name.startswith("dpcorr"):
                # an unrelated object's .counter("x") — only treat the
                # factory form as a metric when the name already claims
                # the namespace OR the receiver is registry-shaped
                if not any(tok in chain[0].lower()
                           for tok in ("registry", "reg", "metrics")):
                    continue
            if not METRIC_NAME_RE.fullmatch(name):
                yield Violation(
                    "metric-name-style", module.relpath, node.lineno,
                    f"metric name {name!r} must match "
                    f"^dpcorr_[a-z0-9_]*$ — the shared /metrics "
                    f"namespace is dpcorr_-prefixed snake_case")

    # -- span-no-finally ------------------------------------------------
    def _check_spans(self, module: Module, imports,
                     ) -> Iterator[Violation]:
        scopes = [module.tree] + [n for n in walk_all(module.tree)
                                  if isinstance(n, _SCOPES)]
        for scope in scopes:
            yield from self._scan_scope(module, scope)

    def _scan_scope(self, module: Module, scope) -> Iterator[Violation]:
        opens: list[tuple[ast.Call, str | None]] = []
        closed_in_finally: set[str] = set()
        for node in _walk_scope(scope):
            if isinstance(node, ast.Try) and node.finalbody:
                for fin in node.finalbody:
                    for sub in ast.walk(fin):
                        if (isinstance(sub, ast.Call)
                                and isinstance(sub.func, ast.Attribute)
                                and sub.func.attr == "end"
                                and isinstance(sub.func.value, ast.Name)):
                            closed_in_finally.add(sub.func.value.id)
            if not isinstance(node, ast.Call):
                continue
            # match the attribute tail directly: `tracer().start_span`
            # and `self.tracer.start_span` both count (call_chain breaks
            # on the intermediate call in the former)
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "start_span"):
                continue
            opens.append((node, _bound_name(node, scope)))
        for call, target in opens:
            if target is not None and target in closed_in_finally:
                continue
            yield Violation(
                "span-no-finally", module.relpath, call.lineno,
                "span opened with start_span() is not closed in a "
                "finally in this scope — on an exception path it "
                "leaks (never emitted); prefer `with tracer.span(...)`")


def _walk_scope(scope) -> Iterator[ast.AST]:
    """Walk ``scope`` without descending into nested function scopes
    (a closure has its own lifecycle and is scanned as its own scope)."""
    roots = (scope.body if isinstance(scope, (ast.Module, *_SCOPES))
             and not isinstance(scope, ast.Lambda) else [scope])
    if isinstance(scope, ast.Lambda):
        roots = [scope.body]
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _SCOPES):
            continue  # a nested def/lambda is its own scope
        stack.extend(ast.iter_child_nodes(node))


def _bound_name(call: ast.Call, scope) -> str | None:
    """The simple name ``x`` when the call is the value of ``x = ...``
    in this scope, else None (attribute targets, list comprehensions
    and bare expressions cannot be tracked and stay flagged)."""
    for node in _walk_scope(scope):
        if (isinstance(node, ast.Assign) and node.value is call
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            return node.targets[0].id
    return None
