"""Durability discipline for the WAL/journal/snapshot namespaces
(``--deep``).

The crash-exactness story (docs/ROBUSTNESS.md) rests on two write
idioms, both already canonical in the tree:

- **append + flush + fsync** before acknowledging (stream/wal.py
  ``_append_line``, serve/budget_dir.py ``_wal_append_locked``);
- **tmp + fsync + os.replace** for snapshots (obs ``_atomic_write``,
  serve/ledger.py ``_persist_locked``, protocol/journal.py
  ``_persist``), with a stale-``.tmp`` sweep on startup and a
  ``.corrupt`` quarantine on the load path
  (obs/budget_replay.py ``sweep_stale_tmp``/``quarantine_corrupt``).

This rule family makes the idioms checkable so the next durable
artifact cannot be added with a bare ``open(..., "w")``. A module is in
the durable namespace when its filename names one of the durable
artifact kinds (``wal``/``journal``/``ledger``/``budget``/``snapshot``/
``checkpoint`` — path-based, like every other scope in this linter).
Within such a module:

- ``durability-bare-write`` — a write-mode ``open`` whose function
  cannot reach the required discipline through the call graph: an
  append with no ``fsync`` reachable, a ``.tmp`` write missing
  ``fsync`` or ``os.replace``, or a direct ``"w"`` on the durable path
  (the torn-file shape ``os.replace`` exists to prevent).
- ``durability-unsynced-ack`` — an appending function returns a value
  (the ack: a seq, an offset) on a path where no ``fsync`` happened
  after the append — the caller proceeds believing the record is
  durable while it still sits in the page cache.
- ``durability-missing-sweep`` — the module replaces into its
  namespace but no function in it reaches a stale-``.tmp`` sweep: a
  crash between tmp-write and replace leaves orphans forever.
- ``durability-missing-quarantine`` — the module replaces into its
  namespace but has no ``.corrupt`` quarantine on its load path: a
  torn artifact would be re-parsed (and crash-loop) instead of being
  set aside for forensics.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from dpcorr.analysis.callgraph import FunctionInfo, ProjectModel
from dpcorr.analysis.core import ProjectChecker, Violation, \
    attr_chain, walk_same_scope

#: filename pattern that places a module in the durable namespace.
_DURABLE_RE = re.compile(
    r"(wal|journal|ledger|budget|snapshot|checkpoint)", re.IGNORECASE)


def _is_durable_module(relpath: str) -> bool:
    parts = relpath.split("/")
    if "analysis" in parts:        # the linter's own rule modules
        return False
    return bool(_DURABLE_RE.search(parts[-1]))


def _open_mode(call: ast.Call) -> str | None:
    """The mode string of an ``open``-like call, or None when it can't
    be determined statically."""
    chain = attr_chain(call.func)
    args = list(call.args)
    mode_node = None
    if chain == ("open",):
        if len(args) >= 2:
            mode_node = args[1]
    elif args:
        mode_node = args[0]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return "r" if chain == ("open",) and len(args) < 2 else None
    if isinstance(mode_node, ast.Constant) and \
            isinstance(mode_node.value, str):
        return mode_node.value
    return None


def _target_text(call: ast.Call) -> str:
    chain = attr_chain(call.func)
    if chain == ("open",) and call.args:
        try:
            return ast.unparse(call.args[0])
        except Exception:
            return ""
    return ".".join(chain[:-1])


class DurabilityChecker(ProjectChecker):
    name = "durability"
    rules = {
        "durability-bare-write": "write into a durable namespace "
                                 "without the append+fsync or "
                                 "tmp+fsync+os.replace idiom",
        "durability-unsynced-ack": "append function returns (acks) "
                                   "before any fsync of the record",
        "durability-missing-sweep": "os.replace namespace with no "
                                    "stale-.tmp sweep reachable",
        "durability-missing-quarantine": "os.replace namespace with no "
                                         ".corrupt quarantine on the "
                                         "load path",
    }

    def check_project(self, model: ProjectModel) -> Iterator[Violation]:
        for module in model.modules:
            if _is_durable_module(module.relpath):
                yield from self._check_module(model, module.relpath)

    # -------------------------------------------------- one module ----
    def _check_module(self, model: ProjectModel,
                      relpath: str) -> Iterator[Violation]:
        fns = [fi for fi in model.functions.values()
               if fi.relpath == relpath]
        replace_lines: list[int] = []
        has_sweep = has_quarantine = False
        for fi in fns:
            effects = model.transitive_effects(fi.key)
            if "sweep" in effects:
                has_sweep = True
            if "quarantine" in effects:
                has_quarantine = True
            for eff in fi.effects:
                if eff.kind == "replace":
                    replace_lines.append(eff.lineno)
            yield from self._check_fn(model, fi)
        if not replace_lines:
            return
        module = model.by_relpath[relpath]
        anchor = min(replace_lines)
        if not has_sweep:
            yield Violation(
                "durability-missing-sweep", relpath, anchor,
                "this module os.replace()s durable artifacts but never "
                "reaches a stale-.tmp sweep (obs.budget_replay."
                "sweep_stale_tmp) — a crash between tmp-write and "
                "replace leaves orphan .tmp files forever")
        if not has_quarantine and ".corrupt" not in module.source:
            yield Violation(
                "durability-missing-quarantine", relpath, anchor,
                "this module os.replace()s durable artifacts but has "
                "no .corrupt quarantine on its load path (obs."
                "budget_replay.quarantine_corrupt) — a torn artifact "
                "would crash-loop instead of being set aside")

    # ------------------------------------------------ one function ----
    def _check_fn(self, model: ProjectModel,
                  fi: FunctionInfo) -> Iterator[Violation]:
        opens: list[tuple[ast.Call, str, str]] = []
        for node in walk_same_scope(fi.node):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain and chain[-1] == "open":
                    mode = _open_mode(node)
                    if mode and any(c in mode for c in "wax+"):
                        opens.append((node, mode, _target_text(node)))
        if not opens:
            return
        effects = model.transitive_effects(fi.key)
        fsync_chain = effects.get("fsync")
        replace_chain = effects.get("replace")
        for call, mode, target in opens:
            if "a" in mode:
                if fsync_chain is None:
                    yield Violation(
                        "durability-bare-write", fi.relpath, call.lineno,
                        f"appends to durable path {target or '<path>'} "
                        f"but no fsync is reachable from "
                        f"{fi.qualname} — the record can be lost from "
                        f"the page cache on crash",
                        chain=(fi.site(call.lineno),))
                else:
                    yield from self._check_ack(model, fi, call)
            elif "tmp" in target.lower():
                # covers both literal ".tmp" suffixes and the repo's
                # convention of a `tmp = path + ".tmp"` local — the
                # unparsed target is then just the variable name
                missing = [k for k, c in (("fsync", fsync_chain),
                                          ("os.replace", replace_chain))
                           if c is None]
                if missing:
                    yield Violation(
                        "durability-bare-write", fi.relpath, call.lineno,
                        f"tmp-writes {target} but "
                        f"{' and '.join(missing)} "
                        f"{'is' if len(missing) == 1 else 'are'} not "
                        f"reachable from {fi.qualname} — the "
                        f"tmp+fsync+os.replace idiom is incomplete",
                        chain=(fi.site(call.lineno),))
            else:
                yield Violation(
                    "durability-bare-write", fi.relpath, call.lineno,
                    f"bare open({target or '<path>'}, {mode!r}) in a "
                    f"durable namespace — write a .tmp sibling, fsync, "
                    f"then os.replace (a crash mid-write here tears "
                    f"the artifact in place)",
                    chain=(fi.site(call.lineno),))

    def _check_ack(self, model: ProjectModel, fi: FunctionInfo,
                   open_call: ast.Call) -> Iterator[Violation]:
        """fsync-before-ack: every value-return after the append must
        have an fsync-reaching line between the open and the return."""
        fsync_lines = sorted(
            {eff.lineno for eff in fi.effects if eff.kind == "fsync"} |
            {cs.lineno for cs in fi.calls if cs.target is not None
             and "fsync" in model.transitive_effects(cs.target)})
        for node in walk_same_scope(fi.node):
            if not (isinstance(node, ast.Return)
                    and node.value is not None):
                continue
            if isinstance(node.value, ast.Constant) and \
                    node.value.value is None:
                continue
            if node.lineno <= open_call.lineno:
                continue
            if not any(open_call.lineno <= f <= node.lineno
                       for f in fsync_lines):
                yield Violation(
                    "durability-unsynced-ack", fi.relpath, node.lineno,
                    f"{fi.qualname} acks (returns a value) after "
                    f"appending at line {open_call.lineno} with no "
                    f"fsync in between — the caller proceeds on a "
                    f"record still in the page cache",
                    chain=(fi.site(node.lineno),))
