"""One compile path: every AOT build goes through ``utils.compile``.

The repo's ahead-of-time story (export round-trips, compile-event
observability, SingleFlight dedup, the AOT-vs-lazy-jit fallback
contract) all hangs off one function —
:func:`dpcorr.utils.compile.aot_compile` — and through it the plan
layer (``dpcorr.plan.Executor.prepare``). A private
``jit(...).lower(...).compile()`` anywhere else silently opts out of
all of it: the compile is invisible to ``dpcorr_compile_*`` metrics,
races other builders of the same signature, and never participates in
the export cache. The grid, serve, federation and roofline dispatch
sites were each exactly that bug before ISSUE 19 ported them. One rule:

- ``aot-outside-compile-layer`` — a ``.lower(...).compile(...)`` call
  chain in any scanned module other than ``utils/compile.py`` itself.

The chain match requires the ``.compile()`` receiver to be a
``.lower(...)`` *call*, so ``str.lower()`` and config objects with a
``compile`` method never fire. The committed baseline carries zero
entries for this rule: there is no legacy site to grandfather, and any
new finding is a regression, not debt.
"""

from __future__ import annotations

import ast
from typing import Iterator

from dpcorr.analysis.core import Checker, Module, Violation, walk_all


class CompilePathChecker(Checker):
    name = "compilepath"
    rules = {
        "aot-outside-compile-layer":
            ".lower(...).compile() outside utils/compile.py — AOT "
            "builds go through utils.compile.aot_compile (or "
            "plan.Executor.prepare) so they are observed, deduplicated "
            "and exportable",
    }

    def applies_to(self, relpath: str) -> bool:
        # everything except the one sanctioned site
        return not relpath.endswith("utils/compile.py")

    def check(self, module: Module) -> Iterator[Violation]:
        for node in walk_all(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "compile"):
                continue
            recv = fn.value
            if not (isinstance(recv, ast.Call)
                    and isinstance(recv.func, ast.Attribute)
                    and recv.func.attr == "lower"):
                continue
            yield Violation(
                "aot-outside-compile-layer", module.relpath, node.lineno,
                ".lower(...).compile() builds an AOT executable outside "
                "the compile layer — route it through "
                "utils.compile.aot_compile / plan.Executor.prepare")
