"""jit purity: no host side effects inside traced functions.

A function handed to ``jax.jit``/``vmap``/``lax.map``/``pallas_call``
runs **once** at trace time; everything that is not a jax op is baked
into the compiled program. Host effects inside therefore do the wrong
thing silently: ``time.time()`` freezes the trace-time clock into every
call, ``print`` fires once (or per recompile) instead of per call,
Python/NumPy ``random`` draws a single constant (breaking *both*
reproducibility and the DP noise analysis — a "random" draw that is
the same constant every call has sensitivity 0 budget but leaks like a
constant shift), and mutating closed-over state from inside a trace is
a classic source of cache-dependent results. Two rules:

- ``jit-impure-call`` — a call with host side effects (wall clocks,
  ``print``, stdlib/NumPy RNG, ``os.urandom``/``secrets``, file I/O)
  lexically inside a traced function.
- ``jit-closure-mutation`` — ``global``/``nonlocal`` declarations or
  in-place mutation of a closed-over (free) variable inside a traced
  function: the mutation happens at trace time, not at call time, and
  its visibility depends on jit's cache.

Traced contexts are found both ways jax is used in this repo: as
decorators (``@jax.jit``, ``@partial(jax.jit, ...)``) and as call
arguments (``jax.jit(f)``, ``lax.map(f, xs)``, ``vmap(f)``,
``pl.pallas_call(kernel, ...)``, ``shard_map(f, ...)``), following
through ``partial(...)`` and nested wrappers (``jit(vmap(f))``) and
resolving bare names to local ``def``s in the same module.
"""

from __future__ import annotations

import ast
from typing import Iterator

from dpcorr.analysis.core import (
    Checker,
    Module,
    Violation,
    attr_chain,
    call_chain,
    imported_names,
    walk_all,
    walk_same_scope,
)

#: callable tails that trace their function argument(s).
TRACER_TAILS = frozenset({"jit", "vmap", "pmap", "pallas_call",
                          "shard_map", "checkify", "grad", "value_and_grad"})
#: `map` only traces when it is lax's (builtin map is host-side).
_LAX_MAP_ORIGINS = ("jax.lax.map", "jax.lax.scan", "jax.lax.fori_loop",
                    "jax.lax.while_loop", "jax.lax.cond", "jax.lax.switch")

#: dotted-origin prefixes whose calls are host side effects.
IMPURE_PREFIXES = (
    "time.", "random.", "numpy.random.", "os.urandom", "secrets.",
    "datetime.datetime.now", "datetime.date.today", "uuid.",
)
IMPURE_BUILTINS = frozenset({"print", "input", "open", "exec", "eval"})

#: in-place mutators for the closure-mutation rule.
MUTATOR_FNS = frozenset({
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "remove", "reverse", "setdefault",
    "sort", "update", "write",
})


class PurityChecker(Checker):
    name = "purity"
    rules = {
        "jit-impure-call": "host side effect (clock/print/stdlib RNG/"
                           "I/O) inside a traced function",
        "jit-closure-mutation": "closed-over state mutated inside a "
                                "traced function",
    }

    def check(self, module: Module) -> Iterator[Violation]:
        imports = imported_names(module.tree)
        defs = self._local_defs(module.tree)
        traced: list[ast.AST] = []
        seen: set[int] = set()

        def mark(fn_node) -> None:
            if fn_node is not None and id(fn_node) not in seen:
                seen.add(id(fn_node))
                traced.append(fn_node)

        for node in walk_all(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if self._is_tracer(deco, imports):
                        mark(node)
            if isinstance(node, ast.Call) and \
                    self._is_tracer(node, imports):
                for arg in self._fn_args(node):
                    mark(self._resolve(arg, defs))
        # expand to nested scopes once, deduped — a lambda inside a jit
        # that is *also* handed to lax.map must be checked exactly once
        scopes: dict[int, ast.AST] = {}
        for fn in traced:
            scopes.setdefault(id(fn), fn)
            for node in ast.walk(fn):
                if node is not fn and isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
                    scopes.setdefault(id(node), node)
        for scope in scopes.values():
            yield from self._check_scope(module, scope, imports)

    # --------------------------------------------- traced-context set ----
    @staticmethod
    def _local_defs(tree) -> dict[str, ast.AST]:
        return {n.name: n for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _is_tracer(self, node, imports) -> bool:
        """Is this decorator/call expression a tracing transform?"""
        if isinstance(node, ast.Call):
            return self._is_tracer(node.func, imports) \
                or self._is_partial_of_tracer(node, imports)
        chain = ()
        if isinstance(node, (ast.Attribute, ast.Name)):
            chain = attr_chain(node)
        if not chain:
            return False
        origin = self._origin(chain, imports)
        if chain[-1] in TRACER_TAILS and not origin.startswith("numpy"):
            return True
        return origin in _LAX_MAP_ORIGINS or \
            (chain[-1] == "map" and len(chain) >= 2
             and chain[-2] == "lax")

    def _is_partial_of_tracer(self, call: ast.Call, imports) -> bool:
        chain = attr_chain(call.func)
        if not chain or chain[-1] != "partial":
            return False
        return bool(call.args) and self._is_tracer(call.args[0], imports)

    def _fn_args(self, call: ast.Call):
        """The candidate function-valued arguments of a tracing call.
        All positional args are yielded (``lax.cond``/``fori_loop``
        take their functions mid-signature); :meth:`_resolve` discards
        the non-function ones."""
        args = list(call.args)
        chain = attr_chain(call.func)
        if chain and chain[-1] == "partial":
            args = args[1:]  # partial(jax.jit, static...) — skip jit
        yield from args

    def _resolve(self, arg, defs) -> ast.AST | None:
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            return defs.get(arg.id)
        if isinstance(arg, ast.Call):
            # nested wrapper: vmap(f) inside jit(vmap(f))
            for inner in arg.args:
                r = self._resolve(inner, defs)
                if r is not None:
                    return r
        return None

    # ------------------------------------------------------- checking ----
    def _check_scope(self, module: Module, scope, imports,
                     ) -> Iterator[Violation]:
        """Check one traced scope against its own local-binding set
        (nested defs/lambdas were expanded into their own scopes)."""
        local = self._local_bindings(scope)
        for node in walk_same_scope(scope):
            if node is scope:
                continue
            yield from self._check_node(module, node, imports, local)

    def _check_node(self, module: Module, node, imports, local,
                    ) -> Iterator[Violation]:
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kw = "global" if isinstance(node, ast.Global) else "nonlocal"
            yield Violation(
                "jit-closure-mutation", module.relpath, node.lineno,
                f"`{kw} {', '.join(node.names)}` inside a traced "
                f"function — the rebind happens at trace time, not per "
                f"call")
            return
        if isinstance(node, ast.Call):
            chain = call_chain(node)
            if chain:
                origin = self._origin(chain, imports)
                impure = (
                    any(origin == p.rstrip(".") or origin.startswith(p)
                        for p in IMPURE_PREFIXES)
                    or (len(chain) == 1 and chain[0] in IMPURE_BUILTINS
                        and chain[0] not in local))
                if impure:
                    yield Violation(
                        "jit-impure-call", module.relpath, node.lineno,
                        f"{'.'.join(chain)}(...) has host side effects "
                        f"— it runs once at trace time, not per call")
                # mutating method on a free variable
                if len(chain) >= 2 and chain[-1] in MUTATOR_FNS \
                        and chain[0] not in local and chain[0] != "self":
                    yield Violation(
                        "jit-closure-mutation", module.relpath,
                        node.lineno,
                        f"{'.'.join(chain)}(...) mutates closed-over "
                        f"state inside a traced function")
            return
        # store to a subscript/attribute rooted at a free variable
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                root = t
                while isinstance(root, (ast.Subscript, ast.Attribute)):
                    root = root.value
                if isinstance(root, ast.Name) and root is not t \
                        and root.id not in local and root.id != "self":
                    yield Violation(
                        "jit-closure-mutation", module.relpath,
                        node.lineno,
                        f"store into closed-over {root.id!r} inside a "
                        f"traced function")

    @staticmethod
    def _local_bindings(scope) -> set[str]:
        """Names bound in this function scope: params plus every Store
        target (conservatively including comprehension vars)."""
        names: set[str] = set()
        args = scope.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            names.add(a.arg)
        for node in walk_same_scope(scope):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Store):
                names.add(node.id)
            elif isinstance(node, ast.ExceptHandler) and node.name:
                names.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    names.add(alias.asname
                              or alias.name.split(".")[0])
        return names

    @staticmethod
    def _origin(chain: tuple[str, ...], imports: dict[str, str]) -> str:
        root = imports.get(chain[0], chain[0])
        return ".".join((root,) + chain[1:])
