"""Interprocedural budget discipline (``--deep``).

rules/budget.py checks charge→enqueue dominance and refund guards one
function at a time, and deliberately stays silent when the charge and
the enqueue live in different functions — which, since the
CompositeLedger/gate refactors, is the *common* shape: an admission
method calls ``self._admit()`` (which charges) and then hands the work
to ``self.coalescer.submit(...)``, or charges directly and launches
through a private ``_launch()`` helper. This pass closes that gap by
inlining callee summaries through the call graph (depth-capped):

- ``budget-deep-uncharged-enqueue`` — composing the function with its
  resolved callees, an enqueue (direct or inherited from a callee)
  executes before the first charge: work can launch unpaid even
  though each individual function looked fine.
- ``budget-deep-missing-refund`` — a post-charge enqueue inherited
  across a function boundary is refund-guarded neither where it
  physically lives nor at the call site that inherits it: a refusal
  would strand the charge.

Findings where every charge *and* every enqueue is direct are left to
the intra-function rule (no double reporting), and an enqueue whose
originating call site also produces a charge (e.g. a call to
``gate.send_release``, which charges, sends and refunds internally) is
trusted to that callee — the intra rule already audits its body.
"""

from __future__ import annotations

import ast
from typing import Iterator

from dpcorr.analysis.callgraph import FunctionInfo, ProjectModel
from dpcorr.analysis.core import ProjectChecker, Violation, \
    attr_chain, walk_same_scope
from dpcorr.analysis.rules.budget import (
    CHARGE_FNS,
    _is_enqueue_call,
    _is_ledger_call,
)

#: how many call-graph levels charges/enqueues are inlined through.
_DEPTH = 3


def _refund_guarded(fi: FunctionInfo, lineno: int) -> bool:
    """True when a ``try`` in ``fi`` lexically contains line ``lineno``
    in its body and has a handler that reaches a refund (any call whose
    name chain mentions ``refund`` — the repo convention the shed rule
    also keys on)."""
    for node in walk_same_scope(fi.node):
        if not isinstance(node, ast.Try):
            continue
        in_body = any(getattr(sub, "lineno", None) == lineno
                      for stmt in node.body for sub in ast.walk(stmt))
        if not in_body:
            continue
        for handler in node.handlers:
            for stmt in handler.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and any(
                            "refund" in part
                            for part in attr_chain(sub.func)):
                        return True
    return False


class DeepBudgetChecker(ProjectChecker):
    name = "deepbudget"
    rules = {
        "budget-deep-uncharged-enqueue": "across function boundaries, "
                                         "an enqueue executes before "
                                         "the first ledger charge",
        "budget-deep-missing-refund": "a cross-function post-charge "
                                      "enqueue has no refund guard at "
                                      "either level",
    }

    def applies_to(self, relpath: str) -> bool:
        parts = relpath.split("/")
        return ("serve" in parts or "protocol" in parts
                or "stream" in parts)

    def check_project(self, model: ProjectModel) -> Iterator[Violation]:
        self._direct_memo: dict[str, tuple] = {}
        for key, fi in model.functions.items():
            if not self.applies_to(fi.relpath):
                continue
            yield from self._check_fn(model, key, fi)

    # ----------------------------------------------- direct summary ----
    def _direct(self, model: ProjectModel, key: str) -> tuple:
        """(charge_linenos, [(enqueue_lineno, text, guarded)])."""
        if key in self._direct_memo:
            return self._direct_memo[key]
        fi = model.functions[key]
        charges: list[int] = []
        enqueues: list[tuple[int, str, bool]] = []
        for node in walk_same_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if _is_ledger_call(node, CHARGE_FNS):
                charges.append(node.lineno)
            elif _is_enqueue_call(node):
                enqueues.append((node.lineno,
                                 ".".join(attr_chain(node.func)),
                                 _refund_guarded(fi, node.lineno)))
        self._direct_memo[key] = (charges, enqueues)
        return self._direct_memo[key]

    def _effective(self, model: ProjectModel, key: str, depth: int,
                   stack: frozenset) -> tuple[list, list]:
        """Inlined view: (charges, enqueues) as
        ([(line_in_f, chain)], [(line_in_f, chain, text, guarded)])."""
        fi = model.functions[key]
        d_charges, d_enqueues = self._direct(model, key)
        charges = [(ln, ()) for ln in d_charges]
        enqueues = [(ln, (), text, g) for ln, text, g in d_enqueues]
        if depth <= 0:
            return charges, enqueues
        for cs in fi.calls:
            if cs.target is None or cs.target in stack \
                    or cs.target not in model.functions:
                continue
            sub_c, sub_e = self._effective(model, cs.target, depth - 1,
                                           stack | {key})
            site = fi.site(cs.lineno)
            for _, chain in sub_c:
                charges.append((cs.lineno, (site,) + chain))
            for _, chain, text, g in sub_e:
                enqueues.append((cs.lineno, (site,) + chain, text, g))
        return charges, enqueues

    # ------------------------------------------------- one function ----
    def _check_fn(self, model: ProjectModel, key: str,
                  fi: FunctionInfo) -> Iterator[Violation]:
        charges, enqueues = self._effective(model, key, _DEPTH,
                                            frozenset({key}))
        if not charges or not enqueues:
            return
        if all(not c[1] for c in charges) and \
                all(not e[1] for e in enqueues):
            return                 # purely intra: rules/budget.py owns it
        charge_lines = sorted({ln for ln, _ in charges})
        first_charge = charge_lines[0]
        seen: set[tuple[int, str]] = set()
        for line, chain, text, guarded in enqueues:
            if line in charge_lines:
                continue           # same call site charges too: the
            if (line, text) in seen:  # callee is internally consistent
                continue
            seen.add((line, text))
            if line < first_charge:
                yield Violation(
                    "budget-deep-uncharged-enqueue", fi.relpath, line,
                    f"{text} launches work at line {line} but the "
                    f"first ledger charge in {fi.qualname}'s composed "
                    f"view is at line {first_charge} — a crash (or "
                    f"refusal) in between runs the work unpaid",
                    chain=chain or (fi.site(line),))
            elif not guarded and not _refund_guarded(fi, line):
                yield Violation(
                    "budget-deep-missing-refund", fi.relpath, line,
                    f"{text} can refuse after the ledger was charged "
                    f"(line {first_charge}) and no refund guard exists "
                    f"in {fi.qualname} or where the enqueue lives — "
                    f"a refusal would strand the charge",
                    chain=chain or (fi.site(line),))
