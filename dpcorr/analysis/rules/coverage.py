"""Chaos-point coverage cross-check (``--deep``).

``chaos.KNOWN_POINTS`` is the append-only registry of crash windows
the recovery story claims to survive. A point only earns its keep when
(a) some *live* code path actually calls ``chaos.point("...")`` for it
— a point whose instrumentation site became unreachable after a
refactor tests nothing — and (b) something actually *sweeps* it: the
``dpcorr chaos`` step-kill matrix (``MATRIX_POINTS``) or a named
reference in a benchmark/test/CI sweep. Two rules, both anchored at
the point's registry line in chaos.py so the finding reads like a
registry audit:

- ``chaos-unreachable-point`` — no ``chaos.point("x")`` call site
  exists, or none is reachable (through the call graph, including
  ``Thread(target=...)`` references) from any public entrypoint.
- ``chaos-unswept-point`` — the point is reachable but absent from
  ``MATRIX_POINTS`` and never referenced by name under ``tests/``,
  ``benchmarks/`` or ``.github/`` — no job will ever kill there, so
  the crash window can rot silently.

The registry is located structurally (a module-level ``KNOWN_POINTS``
tuple of string literals), so fixtures can carry their own miniature
registry.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from dpcorr.analysis.callgraph import ProjectModel
from dpcorr.analysis.core import Module, ProjectChecker, Violation, \
    attr_chain, walk_same_scope

#: directories under --root whose text constitutes "swept by a job".
_SWEEP_DIRS = ("tests", "benchmarks", ".github")
_SWEEP_EXTS = (".py", ".yml", ".yaml", ".sh", ".toml", ".cfg")


def _registry(module: Module) -> tuple[dict[str, int], set[str]] | None:
    """(point → registry lineno, matrix set) when the module carries a
    ``KNOWN_POINTS`` tuple of string literals."""
    known: dict[str, int] = {}
    matrix: set[str] = set()
    for node in module.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if name == "KNOWN_POINTS" and isinstance(node.value,
                                                 (ast.Tuple, ast.List)):
            for el in node.value.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    known[el.value] = el.lineno
        elif name == "MATRIX_POINTS" and isinstance(node.value,
                                                    (ast.Tuple,
                                                     ast.List)):
            try:
                matrix = set(ast.literal_eval(node.value))
            except (ValueError, SyntaxError):
                matrix = set()
    return (known, matrix) if known else None


class ChaosCoverageChecker(ProjectChecker):
    name = "coverage"
    rules = {
        "chaos-unreachable-point": "registered chaos point with no "
                                   "point() call site reachable from "
                                   "a public entrypoint",
        "chaos-unswept-point": "reachable chaos point absent from "
                               "MATRIX_POINTS and from every "
                               "benchmark/test/CI sweep",
    }

    def check_project(self, model: ProjectModel) -> Iterator[Violation]:
        registries = [(m, reg) for m in model.modules
                      if (reg := _registry(m)) is not None]
        if not registries:
            return
        # every chaos.point("x") call site, with its enclosing function
        sites: dict[str, list[tuple[str, int]]] = {}
        for key, fi in model.functions.items():
            for node in walk_same_scope(fi.node):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                chain = attr_chain(node.func)
                if not chain or chain[-1] != "point":
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    sites.setdefault(arg.value, []).append(
                        (key, node.lineno))
        # public surface: non-underscore functions, plus dunders of
        # public classes (constructed/invoked from outside the model —
        # tests build the server, the runtime calls __enter__ etc.)
        entrypoints = []
        for key, fi in model.functions.items():
            if not fi.name.startswith("_"):
                entrypoints.append(key)
            elif fi.name.startswith("__") and fi.name.endswith("__"):
                cls = fi.qualname.rpartition(".")[0]
                if not cls.startswith("_"):
                    entrypoints.append(key)
        live = model.reachable(entrypoints)
        corpus = self._sweep_corpus(model.root)
        for module, (known, matrix) in registries:
            for point, lineno in known.items():
                point_sites = sites.get(point, [])
                reachable = [s for s in point_sites if s[0] in live]
                if not reachable:
                    where = ", ".join(
                        f"{model.functions[k].relpath}:{ln}"
                        for k, ln in point_sites) or "nowhere"
                    yield Violation(
                        "chaos-unreachable-point", module.relpath,
                        lineno,
                        f"chaos point {point!r} is registered but no "
                        f"chaos.point() site for it is reachable from "
                        f"a public entrypoint (instrumented at: "
                        f"{where}) — the crash window it names is "
                        f"untested dead code",
                        chain=tuple(f"{model.functions[k].relpath}:{ln}"
                                    f" ({model.functions[k].qualname})"
                                    for k, ln in point_sites))
                    continue
                if point in matrix or point in corpus:
                    continue
                yield Violation(
                    "chaos-unswept-point", module.relpath, lineno,
                    f"chaos point {point!r} is live (e.g. "
                    f"{model.functions[reachable[0][0]].relpath}:"
                    f"{reachable[0][1]}) but is not in MATRIX_POINTS "
                    f"and no test/benchmark/CI sweep names it — no "
                    f"job ever kills there, so its recovery path can "
                    f"rot silently",
                    chain=tuple(f"{model.functions[k].relpath}:{ln}"
                                f" ({model.functions[k].qualname})"
                                for k, ln in reachable))

    @staticmethod
    def _sweep_corpus(root: str) -> str:
        """Concatenated text of every sweep-capable file under the
        root's tests/, benchmarks/ and .github/ trees."""
        parts: list[str] = []
        for d in _SWEEP_DIRS:
            base = os.path.join(root, d)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [n for n in dirnames
                               if not n.startswith(".")
                               and n != "__pycache__"]
                for fn in filenames:
                    if fn.endswith(_SWEEP_EXTS):
                        try:
                            with open(os.path.join(dirpath, fn),
                                      encoding="utf-8",
                                      errors="replace") as f:
                                parts.append(f.read())
                        except OSError:
                            continue
        return "\n".join(parts)
