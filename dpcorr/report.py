"""Figures / reporting (reference layer L6).

Reproduces the reference's three synthetic figure families
(vert-cor.R:600-721, ver-cor-subG.R:338-436) and the HRS ε-sweep panels
(real-data-sims.R:450-506) with matplotlib, writing PDFs like the
reference's ``ggsave`` calls.

Design notes: two fixed series colors (NI blue, INT orange — a
colorblind-safe pair, assigned by entity and never re-cycled), one y-axis
per panel, recessive dotted grid, reference lines dashed. Each function
takes the grid/sweep summary frames produced by :mod:`dpcorr.grid` /
:mod:`dpcorr.hrs` and returns the matplotlib figure (also saved when
``out`` is given).
"""

from __future__ import annotations

from pathlib import Path

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np
import pandas as pd

#: fixed series colors — NI is always blue, INT always orange
COLORS = {"NI": "#3b6fb5", "INT": "#e07b39"}
_GRID_KW = dict(color="#cccccc", linestyle=":", linewidth=0.6)


def _style(ax, xlabel, ylabel, title=None):
    ax.grid(True, **_GRID_KW)
    ax.set_axisbelow(True)
    ax.spines[["top", "right"]].set_visible(False)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    if title:
        ax.set_title(title, fontsize=10)


def _save(fig, out):
    if out:
        out = Path(out)
        out.parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(out, bbox_inches="tight")
    return fig


def fig_mean_band_vs_rho(detail_all: pd.DataFrame, n: int,
                         eps_pair: tuple[float, float], out=None):
    """Family 1 (vert-cor.R:600-661): mean estimate offset and mean CI-end
    offsets vs true ρ, at one (n, ε) slice. Offsets = value − ρ_true, so a
    perfect estimator hugs the zero line."""
    d = detail_all[(detail_all.n == n) & (detail_all.eps1 == eps_pair[0])
                   & (detail_all.eps2 == eps_pair[1])]
    fig, axes = plt.subplots(1, 2, figsize=(9, 3.4), sharey=True)
    for ax, meth in zip(axes, ("NI", "INT")):
        p = meth.lower()
        g = d.groupby("rho_true")
        rho = np.array(sorted(d.rho_true.unique()))
        mean_off = g[f"{p}_hat"].mean().reindex(rho) - rho
        lo_off = g[f"{p}_low"].mean().reindex(rho) - rho
        hi_off = g[f"{p}_up"].mean().reindex(rho) - rho
        c = COLORS[meth]
        ax.axhline(0.0, color="#888888", linestyle="--", linewidth=0.8)
        ax.fill_between(rho, lo_off, hi_off, color=c, alpha=0.18,
                        label="mean CI band")
        ax.plot(rho, mean_off, color=c, linewidth=2, marker="o",
                markersize=4, label="mean offset")
        _style(ax, r"true $\rho$", "offset from truth",
               f"{meth}  (n={n}, ε=({eps_pair[0]}, {eps_pair[1]}))")
        ax.legend(frameon=False, fontsize=8)
    fig.tight_layout()
    return _save(fig, out)


def fig_width_coverage_vs_n(summ_all: pd.DataFrame, rho: float,
                            alpha: float = 0.05, out=None):
    """Family 2 (vert-cor.R:663-694): CI width and empirical coverage vs n
    at one ρ, per ε-pair; dashed nominal-coverage line."""
    d = summ_all[summ_all.rho_true == rho]
    eps_pairs = sorted(set(zip(d.eps1, d.eps2)))
    fig, axes = plt.subplots(1, 2, figsize=(9, 3.4))
    for meth in ("NI", "INT"):
        s = d[d.method == meth]
        for (e1, e2) in eps_pairs:
            se = s[(s.eps1 == e1) & (s.eps2 == e2)].sort_values("n")
            ls = "-" if (e1, e2) == eps_pairs[0] else \
                 ("--" if (e1, e2) == eps_pairs[min(1, len(eps_pairs) - 1)]
                  else ":")
            axes[0].plot(se.n, se.ci_len, color=COLORS[meth], linestyle=ls,
                         marker="o", markersize=3, linewidth=1.6,
                         label=f"{meth} ε=({e1},{e2})")
            axes[1].plot(se.n, se.coverage, color=COLORS[meth], linestyle=ls,
                         marker="o", markersize=3, linewidth=1.6)
    axes[1].axhline(1 - alpha, color="#888888", linestyle="--", linewidth=0.8)
    _style(axes[0], "n", "mean CI length", f"CI width vs n (ρ={rho})")
    _style(axes[1], "n", "empirical coverage", f"coverage vs n (ρ={rho})")
    axes[0].legend(frameon=False, fontsize=7)
    fig.tight_layout()
    return _save(fig, out)


def fig_mse_vs_n(summ_all: pd.DataFrame, rho: float, out=None):
    """Family 3 (vert-cor.R:696-721): MSE vs n at one ρ (log-y), per ε."""
    d = summ_all[summ_all.rho_true == rho]
    eps_pairs = sorted(set(zip(d.eps1, d.eps2)))
    fig, ax = plt.subplots(figsize=(5.2, 3.6))
    for meth in ("NI", "INT"):
        s = d[d.method == meth]
        for j, (e1, e2) in enumerate(eps_pairs):
            se = s[(s.eps1 == e1) & (s.eps2 == e2)].sort_values("n")
            ax.plot(se.n, se.mse, color=COLORS[meth],
                    linestyle=["-", "--", ":"][j % 3], marker="o",
                    markersize=3, linewidth=1.6,
                    label=f"{meth} ε=({e1},{e2})")
    ax.set_yscale("log")
    _style(ax, "n", "MSE", f"MSE vs n (ρ={rho})")
    ax.legend(frameon=False, fontsize=7)
    fig.tight_layout()
    return _save(fig, out)


def fig_hrs_sweep(summ: pd.DataFrame, rho_np: float | None = None, out=None):
    """HRS ε-sweep panels (real-data-sims.R:450-506): per method, mean
    estimate with mean-CI error bars vs ε, dashed non-private baseline,
    solid zero line; shared y-limits across the two panels."""
    if rho_np is None:
        rho_np = summ.attrs.get("rho_np")
    fig, axes = plt.subplots(1, 2, figsize=(9, 3.4), sharey=True)
    ylo = summ.ci_low_mean.min()
    yhi = summ.ci_high_mean.max()
    pad = 0.05 * (yhi - ylo)
    for ax, meth in zip(axes, ("NI", "INT")):
        s = summ[summ.method == meth].sort_values("eps_corr")
        c = COLORS[meth]
        ax.axhline(0.0, color="#b03030", linewidth=0.9)
        if rho_np is not None:
            ax.axhline(rho_np, color="#555555", linestyle="--", linewidth=0.9,
                       label=r"non-private $\rho$")
        ax.errorbar(s.eps_corr, s.rho_hat_mean,
                    yerr=[s.rho_hat_mean - s.ci_low_mean,
                          s.ci_high_mean - s.rho_hat_mean],
                    color=c, fmt="o-", markersize=3.5, linewidth=1.6,
                    elinewidth=1.0, capsize=2, label=f"{meth} mean ± mean CI")
        ax.set_ylim(ylo - pad, yhi + pad)
        _style(ax, r"$\varepsilon$", r"$\hat\rho$", f"{meth} (AGE→BMI)")
        ax.legend(frameon=False, fontsize=8)
    fig.tight_layout()
    return _save(fig, out)


def render_all(grid_detail: pd.DataFrame | None = None,
               grid_summ: pd.DataFrame | None = None,
               hrs_summ: pd.DataFrame | None = None,
               out_dir: str | Path = "figures",
               fig1_n: int = 1500, fig1_eps=(1.5, 0.5),
               fig23_rho: float = 0.5) -> list[Path]:
    """Render every available figure family into ``out_dir``; returns the
    written paths. Mirrors the reference's end-of-script figure dumps."""
    out_dir = Path(out_dir)
    written = []
    if grid_detail is not None:
        p = out_dir / "fig1_mean_band_vs_rho.pdf"
        fig_mean_band_vs_rho(grid_detail, fig1_n, fig1_eps, out=p)
        written.append(p)
    if grid_summ is not None:
        p = out_dir / "fig2_width_coverage_vs_n.pdf"
        fig_width_coverage_vs_n(grid_summ, fig23_rho, out=p)
        written.append(p)
        p = out_dir / "fig3_mse_vs_n.pdf"
        fig_mse_vs_n(grid_summ, fig23_rho, out=p)
        written.append(p)
    if hrs_summ is not None:
        p = out_dir / "hrs_eps_sweep.pdf"
        fig_hrs_sweep(hrs_summ, out=p)
        written.append(p)
    plt.close("all")
    return written
