"""Figures / reporting (reference layer L6).

Reproduces the reference's three synthetic figure families
(vert-cor.R:600-721, ver-cor-subG.R:338-436) and the HRS ε-sweep panels
(real-data-sims.R:450-506) with matplotlib, writing PDFs like the
reference's ``ggsave`` calls.

Design notes: two fixed series colors (NI blue, INT orange — a
colorblind-safe pair, assigned by entity and never re-cycled), one y-axis
per panel, recessive dotted grid, reference lines dashed. Each function
takes the grid/sweep summary frames produced by :mod:`dpcorr.grid` /
:mod:`dpcorr.hrs` and returns the matplotlib figure (also saved when
``out`` is given).
"""

from __future__ import annotations

from pathlib import Path

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np
import pandas as pd

#: fixed series colors — NI is always blue, INT always orange
COLORS = {"NI": "#3b6fb5", "INT": "#e07b39"}
_GRID_KW = dict(color="#cccccc", linestyle=":", linewidth=0.6)


def _style(ax, xlabel, ylabel, title=None):
    ax.grid(True, **_GRID_KW)
    ax.set_axisbelow(True)
    ax.spines[["top", "right"]].set_visible(False)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    if title:
        ax.set_title(title, fontsize=10)


def _save(fig, out):
    if out:
        out = Path(out)
        out.parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(out, bbox_inches="tight")
    return fig


def fig_mean_band_vs_rho(detail_all: pd.DataFrame, n: int,
                         eps_pair: tuple[float, float], out=None):
    """Family 1 (vert-cor.R:600-661): mean estimate offset and mean CI-end
    offsets vs true ρ, at one (n, ε) slice. Offsets = value − ρ_true, so a
    perfect estimator hugs the zero line."""
    d = detail_all[(detail_all.n == n) & (detail_all.eps1 == eps_pair[0])
                   & (detail_all.eps2 == eps_pair[1])]
    fig, axes = plt.subplots(1, 2, figsize=(9, 3.4), sharey=True)
    for ax, meth in zip(axes, ("NI", "INT")):
        p = meth.lower()
        g = d.groupby("rho_true")
        rho = np.array(sorted(d.rho_true.unique()))
        mean_off = g[f"{p}_hat"].mean().reindex(rho) - rho
        lo_off = g[f"{p}_low"].mean().reindex(rho) - rho
        hi_off = g[f"{p}_up"].mean().reindex(rho) - rho
        c = COLORS[meth]
        ax.axhline(0.0, color="#888888", linestyle="--", linewidth=0.8)
        ax.fill_between(rho, lo_off, hi_off, color=c, alpha=0.18,
                        label="mean CI band")
        ax.plot(rho, mean_off, color=c, linewidth=2, marker="o",
                markersize=4, label="mean offset")
        _style(ax, r"true $\rho$", "offset from truth",
               f"{meth}  (n={n}, ε=({eps_pair[0]}, {eps_pair[1]}))")
        ax.legend(frameon=False, fontsize=8)
    fig.tight_layout()
    return _save(fig, out)


def fig_width_coverage_vs_n(summ_all: pd.DataFrame, rho: float,
                            alpha: float = 0.05, out=None):
    """Family 2 (vert-cor.R:663-694): CI width and empirical coverage vs n
    at one ρ, per ε-pair; dashed nominal-coverage line."""
    d = summ_all[summ_all.rho_true == rho]
    eps_pairs = sorted(set(zip(d.eps1, d.eps2)))
    fig, axes = plt.subplots(1, 2, figsize=(9, 3.4))
    for meth in ("NI", "INT"):
        s = d[d.method == meth]
        for (e1, e2) in eps_pairs:
            se = s[(s.eps1 == e1) & (s.eps2 == e2)].sort_values("n")
            ls = "-" if (e1, e2) == eps_pairs[0] else \
                 ("--" if (e1, e2) == eps_pairs[min(1, len(eps_pairs) - 1)]
                  else ":")
            axes[0].plot(se.n, se.ci_len, color=COLORS[meth], linestyle=ls,
                         marker="o", markersize=3, linewidth=1.6,
                         label=f"{meth} ε=({e1},{e2})")
            axes[1].plot(se.n, se.coverage, color=COLORS[meth], linestyle=ls,
                         marker="o", markersize=3, linewidth=1.6)
    axes[1].axhline(1 - alpha, color="#888888", linestyle="--", linewidth=0.8)
    _style(axes[0], "n", "mean CI length", f"CI width vs n (ρ={rho})")
    _style(axes[1], "n", "empirical coverage", f"coverage vs n (ρ={rho})")
    axes[0].legend(frameon=False, fontsize=7)
    fig.tight_layout()
    return _save(fig, out)


def fig_mse_vs_n(summ_all: pd.DataFrame, rho: float, out=None):
    """Family 3 (vert-cor.R:696-721): MSE vs n at one ρ (log-y), per ε."""
    d = summ_all[summ_all.rho_true == rho]
    eps_pairs = sorted(set(zip(d.eps1, d.eps2)))
    fig, ax = plt.subplots(figsize=(5.2, 3.6))
    for meth in ("NI", "INT"):
        s = d[d.method == meth]
        for j, (e1, e2) in enumerate(eps_pairs):
            se = s[(s.eps1 == e1) & (s.eps2 == e2)].sort_values("n")
            ax.plot(se.n, se.mse, color=COLORS[meth],
                    linestyle=["-", "--", ":"][j % 3], marker="o",
                    markersize=3, linewidth=1.6,
                    label=f"{meth} ε=({e1},{e2})")
    ax.set_yscale("log")
    _style(ax, "n", "MSE", f"MSE vs n (ρ={rho})")
    ax.legend(frameon=False, fontsize=7)
    fig.tight_layout()
    return _save(fig, out)


# ---------------------------------------------------------------- subG ----
# The v2 grid's own figure family (ver-cor-subG.R:338-436) — structurally
# distinct from v1: fig1 overlays both methods on ONE panel (NI grey,
# INT steelblue — the reference's scale_fill/colour_manual at :369-372);
# fig2a/2b are separate width/coverage files on a log-x axis with one color
# per ε-pair and linetype by method; fig3 is log-log.

_SUBG_FILL = {"NI": "#b3b3b3", "INT": "#4682b4"}   # grey70 / steelblue
_SUBG_LINE = {"NI": "#595959", "INT": "#4682b4"}   # grey35 / steelblue
#: one color per ε-pair for the subG vs-n figures (colorblind-safe trio)
_EPS_COLORS = ("#3b6fb5", "#e07b39", "#4daf8c")
_METH_LS = {"NI": "-", "INT": "--"}


def fig_subg_mean_band(detail_all: pd.DataFrame, n: int = 6000,
                       eps_pair: tuple[float, float] = (1.5, 0.5), out=None):
    """subG_fig1 (ver-cor-subG.R:338-380): mean CI offset bands vs ρ at one
    (n, ε) slice — both methods overlaid on a single panel, dashed zero
    line, y = mean(CI) − ρ. Reference slice: n=6000, ε=(1.5, 0.5)."""
    d = detail_all[(detail_all.n == n) & (detail_all.eps1 == eps_pair[0])
                   & (detail_all.eps2 == eps_pair[1])]
    fig, ax = plt.subplots(figsize=(6.8, 4.4))
    ax.axhline(0.0, color="#888888", linestyle="--", linewidth=0.9)
    rho = np.array(sorted(d.rho_true.unique()))
    g = d.groupby("rho_true")
    for meth in ("NI", "INT"):
        p = meth.lower()
        lo_off = g[f"{p}_low"].mean().reindex(rho) - rho
        hi_off = g[f"{p}_up"].mean().reindex(rho) - rho
        est_off = g[f"{p}_hat"].mean().reindex(rho) - rho
        ax.fill_between(rho, lo_off, hi_off, color=_SUBG_FILL[meth],
                        alpha=0.35, linewidth=0, label=meth)
        ax.plot(rho, est_off, color=_SUBG_LINE[meth], linewidth=1.6)
    _style(ax, r"$\rho$", r"mean(CI) $-$ $\rho$",
           f"Mean CI offset bands — n = {n}, "
           f"ε₁ = {eps_pair[0]}, ε₂ = {eps_pair[1]}")
    ax.legend(frameon=False, fontsize=9, title="Estimator", title_fontsize=9)
    fig.tight_layout()
    return _save(fig, out)


def _fig_subg_vs_n(summ_all: pd.DataFrame, rho: float, ycol: str,
                   ylabel: str, title: str, logy: bool = False,
                   nominal: float | None = None, out=None):
    """Shared body of subG fig2a/2b/3: y vs n (log-x), one color per
    ε-pair, linetype by method (ver-cor-subG.R:383-436)."""
    d = summ_all[summ_all.rho_true == rho]
    eps_pairs = sorted(set(zip(d.eps1, d.eps2)))
    fig, ax = plt.subplots(figsize=(6.0, 4.0))
    for j, (e1, e2) in enumerate(eps_pairs):
        c = _EPS_COLORS[j % len(_EPS_COLORS)]
        for meth in ("NI", "INT"):
            s = d[(d.method == meth) & (d.eps1 == e1)
                  & (d.eps2 == e2)].sort_values("n")
            ax.plot(s.n, s[ycol], color=c, linestyle=_METH_LS[meth],
                    marker="o", markersize=3, linewidth=1.6,
                    label=f"({e1},{e2}) {meth}")
    if nominal is not None:
        ax.axhline(nominal, color="#888888", linestyle="--", linewidth=0.8)
    ax.set_xscale("log")
    if logy:
        ax.set_yscale("log")
    _style(ax, "n (log-scale)", ylabel, title)
    ax.legend(frameon=False, fontsize=7, title="(ε₁,ε₂)  method",
              title_fontsize=7)
    fig.tight_layout()
    return _save(fig, out)


def fig_subg_width(summ_all: pd.DataFrame, rho: float = 0.5, out=None):
    """subG_fig2a (ver-cor-subG.R:383-397): average CI width vs n."""
    return _fig_subg_vs_n(summ_all, rho, "ci_len", "Average CI length",
                          f"Average CI width vs n (ρ = {rho})", out=out)


def fig_subg_coverage(summ_all: pd.DataFrame, rho: float = 0.5,
                      alpha: float = 0.05, out=None):
    """subG_fig2b (ver-cor-subG.R:399-413): coverage vs n, nominal line."""
    return _fig_subg_vs_n(summ_all, rho, "coverage", "Empirical coverage",
                          f"Coverage vs n (ρ = {rho})",
                          nominal=1 - alpha, out=out)


def fig_subg_mse(summ_all: pd.DataFrame, rho: float = 0.5, out=None):
    """subG_fig3 (ver-cor-subG.R:418-436): MSE vs n, log-log."""
    return _fig_subg_vs_n(summ_all, rho, "mse", "MSE (log-scale)",
                          f"MSE of ρ̂ vs n (ρ = {rho})", logy=True, out=out)


def render_all_subg(grid_detail: pd.DataFrame | None = None,
                    grid_summ: pd.DataFrame | None = None,
                    out_dir: str | Path = "figures",
                    fig1_n: int = 6000, fig1_eps=(1.5, 0.5),
                    rho: float = 0.5) -> list[Path]:
    """The v2 grid's four-figure dump with the reference's filenames
    (ver-cor-subG.R:380, 411-413, 434)."""
    out_dir = Path(out_dir)
    written = []
    if grid_detail is not None:
        p = out_dir / "subG_fig1_mean_band.pdf"
        fig_subg_mean_band(grid_detail, fig1_n, fig1_eps, out=p)
        written.append(p)
    if grid_summ is not None:
        for name, fn in (("subG_fig2a_width.pdf", fig_subg_width),
                         ("subG_fig2b_cov.pdf", fig_subg_coverage),
                         ("subG_fig3_mse.pdf", fig_subg_mse)):
            p = out_dir / name
            fn(grid_summ, rho, out=p)
            written.append(p)
    plt.close("all")
    return written


def fig_hrs_sweep(summ: pd.DataFrame, rho_np: float | None = None, out=None):
    """HRS ε-sweep panels (real-data-sims.R:450-506): per method, the
    mean-CI *midpoint* ``(ci_low_mean + ci_high_mean)/2`` as the point
    (real-data-sims.R:459-461 — NOT the mean ρ̂, which differs for
    asymmetric CIs) with mean-CI error bars vs ε, dashed non-private
    baseline, red zero line; shared y-limits spanning the CIs, ρ_np and 0
    (real-data-sims.R:463-468)."""
    if rho_np is None:
        rho_np = summ.attrs.get("rho_np")
    fig, axes = plt.subplots(1, 2, figsize=(9, 3.4), sharey=True)
    y_all = [summ.ci_low_mean.min(), summ.ci_high_mean.max(), 0.0]
    if rho_np is not None:
        y_all.append(rho_np)
    ylo, yhi = min(y_all), max(y_all)
    pad = 0.02 * (yhi - ylo)
    titles = {"NI": "Non-interactive", "INT": "Interactive"}
    for ax, meth in zip(axes, ("NI", "INT")):
        s = summ[summ.method == meth].sort_values("eps_corr")
        mid = (s.ci_low_mean + s.ci_high_mean) / 2.0
        c = COLORS[meth]
        ax.axhline(0.0, color="#b03030", linewidth=0.9)
        if rho_np is not None:
            ax.axhline(rho_np, color="#555555", linestyle="--", linewidth=0.9,
                       label=r"non-private $\rho$")
        ax.errorbar(s.eps_corr, mid,
                    yerr=[mid - s.ci_low_mean, s.ci_high_mean - mid],
                    color=c, fmt="o", markersize=3.5,
                    elinewidth=1.0, capsize=2, label="mean CI (midpoint)")
        ax.set_ylim(ylo - pad, yhi + pad)
        _style(ax, r"$\varepsilon_{corr}$", r"mean(CI) for $\rho$",
               titles[meth])
        ax.legend(frameon=False, fontsize=8)
    fig.tight_layout()
    return _save(fig, out)


def serve_stats_frame(snapshot: dict) -> pd.DataFrame:
    """Flatten a serving stats snapshot (serve.ServeStats.snapshot) into
    a tidy (metric, value) frame — the shape ``benchmarks/serve_load.py``
    prints and a dashboard would ingest. Nested groups flatten with
    dotted keys (``latency_s.p99``, ``ledger.parties.<p>.spent``)."""
    rows = []

    def walk(prefix, obj):
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        else:
            rows.append({"metric": prefix, "value": obj})

    walk("", snapshot)
    return pd.DataFrame(rows, columns=["metric", "value"])


def protocol_transcript_frame(transcript) -> pd.DataFrame:
    """One party's wire transcript (protocol.messages.Transcript JSONL,
    or the entry list from ``read_transcript``) as a tidy per-message
    frame — the protocol-mode sibling of :func:`serve_stats_frame`.
    One row per frame: direction, sequence number, message type, wire
    bytes, retry count, send latency, the ε charged through the release
    gate (0 for ungated traffic) and the trace ID, ordered as logged."""
    from dpcorr.protocol.messages import read_transcript

    entries = (read_transcript(transcript) if isinstance(transcript, str)
               else list(transcript))
    rows = [{"seq": e.get("seq"), "dir": e.get("dir"),
             "type": e.get("wire", {}).get("msg_type"),
             "bytes": e.get("bytes"), "retries": e.get("retries"),
             "latency_s": e.get("latency_s"), "eps": e.get("eps"),
             "trace_id": e.get("trace_id"), "ts": e.get("ts")}
            for e in entries]
    return pd.DataFrame(rows, columns=["seq", "dir", "type", "bytes",
                                       "retries", "latency_s", "eps",
                                       "trace_id", "ts"])


def correlation_matrix_frame(results, plan=None) -> pd.DataFrame:
    """A completed federation matrix (protocol.federation) as a tidy
    per-cell frame — the N-party sibling of
    :func:`protocol_transcript_frame`. ``results`` is one
    ``FederationResult``, a ``{party: FederationResult}`` mapping (what
    ``run_federation_inproc`` returns — each party only sees its own
    cells, the frame is their union), or a plain cells dict
    ``{"i,j": {"rho_hat", "ci_low", "ci_high"}}`` (the CLI JSON).
    Parties must agree bitwise on every shared cell — disagreement
    raises. With ``plan`` each row also carries the cell's column
    labels and venue (``local@P`` or ``link P-Q``)."""
    cells: dict = {}

    def merge(d):
        for key, val in d.items():
            if key in cells and cells[key] != val:
                raise ValueError(f"parties disagree on cell {key}: "
                                 f"{cells[key]} != {val}")
            cells.setdefault(key, val)

    if hasattr(results, "cells"):
        merge(results.cells)
    elif isinstance(results, dict) \
            and all(hasattr(r, "cells") for r in results.values()):
        for r in results.values():
            merge(r.cells)
    else:
        merge(dict(results))
    rows = []
    for key in sorted(cells,
                      key=lambda s: tuple(int(t) for t in s.split(","))):
        i, j = (int(t) for t in key.split(","))
        val = cells[key]
        row = {"i": i, "j": j, "label_x": None, "label_y": None,
               "venue": None, "rho_hat": val["rho_hat"],
               "ci_low": val["ci_low"], "ci_high": val["ci_high"]}
        if plan is not None:
            row["label_x"], row["label_y"] = plan.label(i), plan.label(j)
            v = plan.cell_venue(i, j)
            row["venue"] = (f"local@{v[1]}" if v[0] == "local"
                            else f"link {v[1]}-{v[2]}")
        rows.append(row)
    return pd.DataFrame(rows, columns=["i", "j", "label_x", "label_y",
                                       "venue", "rho_hat", "ci_low",
                                       "ci_high"])


def render_all(grid_detail: pd.DataFrame | None = None,
               grid_summ: pd.DataFrame | None = None,
               hrs_summ: pd.DataFrame | None = None,
               out_dir: str | Path = "figures",
               fig1_n: int = 1500, fig1_eps=(1.5, 0.5),
               fig23_rho: float = 0.5) -> list[Path]:
    """Render every available figure family into ``out_dir``; returns the
    written paths. Mirrors the reference's end-of-script figure dumps."""
    out_dir = Path(out_dir)
    written = []
    if grid_detail is not None:
        p = out_dir / "fig1_mean_band_vs_rho.pdf"
        fig_mean_band_vs_rho(grid_detail, fig1_n, fig1_eps, out=p)
        written.append(p)
    if grid_summ is not None:
        p = out_dir / "fig2_width_coverage_vs_n.pdf"
        fig_width_coverage_vs_n(grid_summ, fig23_rho, out=p)
        written.append(p)
        p = out_dir / "fig3_mse_vs_n.pdf"
        fig_mse_vs_n(grid_summ, fig23_rho, out=p)
        written.append(p)
    if hrs_summ is not None:
        p = out_dir / "hrs_eps_sweep.pdf"
        fig_hrs_sweep(hrs_summ, out=p)
        written.append(p)
    plt.close("all")
    return written
