"""Benchmark: MC replications/sec/chip on the north-star workload.

BASELINE.md: 1M Monte-Carlo reps of the Gaussian NI estimator at n=10k on a
TPU v4-8 (4 chips) in <60 s ⇒ baseline ≈ 1e6/(60·4) ≈ 4166.7 reps/sec/chip.
This script measures the same per-rep work — generate an n=10k correlated
Gaussian pair, privately standardize, sign-batch estimate + CI, emit metrics
— on whatever single chip is available, and prints ONE JSON line.

Two implementations are raced:

- **xla**: the framework's `jit(vmap)` estimator path (`dpcorr.sim`);
- **pallas**: the fused VMEM kernel (`dpcorr.ops.pallas_ni`) with on-chip
  hardware PRNG — TPU only; any failure (or off-TPU host) falls back to xla
  with the failure recorded in the JSON detail.

Each path compiles one fixed-size block, calibrates its wall-clock, then
dispatches its share of the time budget asynchronously with a single fetch
barrier — total wall-clock stays bounded on any chip speed. The headline
value is the faster path's steady-state reps/sec; both appear in detail.
"""

from __future__ import annotations

import json
import time
from functools import partial

import jax
import jax.numpy as jnp

from dpcorr.models.estimators import ci_ni_signbatch
from dpcorr.models.dgp import gen_gaussian
from dpcorr.sim import chunked_vmap
from dpcorr.utils import rng

BASELINE_REPS_PER_SEC_CHIP = 1_000_000 / (60.0 * 4)

N = 10_000
EPS1 = EPS2 = 1.0
RHO = 0.5
ALPHA = 0.05
CHUNK = 2048
BLOCK_REPS = 32 * 1024
BUDGET_PER_PATH_S = 30.0
MAX_BLOCKS = 32


def _metrics(r):
    cover = ((RHO >= r.ci_low) & (RHO <= r.ci_high)).astype(jnp.float32)
    return (jnp.mean((r.rho_hat - RHO) ** 2), jnp.mean(cover),
            jnp.mean(r.ci_high - r.ci_low))


def _one_rep(key):
    xy = gen_gaussian(rng.stream(key, "dgp"), N, jnp.float32(RHO))
    r = ci_ni_signbatch(rng.stream(key, "ni"), xy[:, 0], xy[:, 1], EPS1, EPS2,
                        alpha=ALPHA)
    cover = ((RHO >= r.ci_low) & (RHO <= r.ci_high)).astype(jnp.float32)
    return (r.rho_hat - RHO) ** 2, cover, r.ci_high - r.ci_low


@partial(jax.jit, static_argnums=(1,))
def _xla_block(key, n_reps: int):
    keys = rng.rep_keys(key, n_reps)
    se2, cover, ci_len = chunked_vmap(_one_rep, keys, CHUNK)
    return jnp.mean(se2), jnp.mean(cover), jnp.mean(ci_len)


@partial(jax.jit, static_argnums=(1,))
def _pallas_block(block_idx, n_reps: int):
    from dpcorr.ops.pallas_ni import ni_sign_pallas

    seeds = block_idx * n_reps + jnp.arange(n_reps, dtype=jnp.int32)
    r = ni_sign_pallas(seeds, RHO, N, EPS1, EPS2, alpha=ALPHA,
                       interpret=False)
    return _metrics(r)


def _fetch(out):
    """Host-fetch the scalars — the only reliable completion barrier
    through the remote-TPU tunnel."""
    return tuple(float(x) for x in out)


def _measure(run_block, args_for):
    """Compile, calibrate one block, then dispatch ~BUDGET worth of blocks
    asynchronously and drain once. Returns (reps_per_sec, mean metrics)."""
    _fetch(run_block(args_for(0), BLOCK_REPS))  # compile + warm
    t0 = time.perf_counter()
    _fetch(run_block(args_for(1), BLOCK_REPS))
    dt1 = time.perf_counter() - t0
    n_blocks = max(1, min(MAX_BLOCKS, int(BUDGET_PER_PATH_S / dt1)))

    t0 = time.perf_counter()
    futs = [run_block(args_for(2 + i), BLOCK_REPS) for i in range(n_blocks)]
    outs = [_fetch(f) for f in futs]
    elapsed = time.perf_counter() - t0
    means = tuple(sum(o[j] for o in outs) / len(outs) for j in range(3))
    return n_blocks * BLOCK_REPS / elapsed, means


def _sane(means) -> bool:
    mse, coverage, ci_len = means
    return 0.90 <= coverage <= 0.99 and 0.0 < mse < 0.01 and 0.0 < ci_len < 0.2


def main():
    key = rng.master_key()
    results = {}

    xla_rps, xla_means = _measure(_xla_block,
                                  lambda i: rng.design_key(key, i))
    results["xla"] = {"reps_per_sec": round(xla_rps, 1),
                      "mse": round(xla_means[0], 6),
                      "coverage": round(xla_means[1], 4),
                      "ci_length": round(xla_means[2], 4)}

    pallas_err = None
    if jax.devices()[0].platform == "tpu":
        try:
            p_rps, p_means = _measure(_pallas_block, lambda i: jnp.int32(i))
            if _sane(p_means):
                results["pallas"] = {"reps_per_sec": round(p_rps, 1),
                                     "mse": round(p_means[0], 6),
                                     "coverage": round(p_means[1], 4),
                                     "ci_length": round(p_means[2], 4)}
            else:
                pallas_err = f"sanity check failed: {p_means}"
        except Exception as e:  # fall back to xla, record why
            pallas_err = f"{type(e).__name__}: {e}"[:300]
    else:
        pallas_err = "not on TPU (on-chip PRNG unavailable)"

    best = max(results, key=lambda p: results[p]["reps_per_sec"])
    rps = results[best]["reps_per_sec"]
    print(json.dumps({
        "metric": "mc_reps_per_sec_chip_ni_sign_n10k",
        "value": rps,
        "unit": "reps/sec/chip",
        "vs_baseline": round(rps / BASELINE_REPS_PER_SEC_CHIP, 3),
        "detail": {
            "n": N, "block_reps": BLOCK_REPS, "path": best,
            "paths": results,
            **({"pallas_skipped": pallas_err} if pallas_err else {}),
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
