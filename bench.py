"""Benchmark: MC replications/sec/chip on the north-star workload.

BASELINE.md: 1M Monte-Carlo reps of the Gaussian NI estimator at n=10k on a
TPU v4-8 (4 chips) in <60 s ⇒ baseline ≈ 1e6/(60·4) ≈ 4166.7 reps/sec/chip.
This script measures the same per-rep work — generate an n=10k correlated
Gaussian pair, privately standardize, sign-batch estimate + CI, emit metrics
— on whatever single chip is available, and prints ONE JSON line.
"""

from __future__ import annotations

import json
import time
from functools import partial

import jax
import jax.numpy as jnp

from dpcorr.models.estimators import ci_ni_signbatch
from dpcorr.models.dgp import gen_gaussian
from dpcorr.sim import chunked_vmap
from dpcorr.utils import rng

BASELINE_REPS_PER_SEC_CHIP = 1_000_000 / (60.0 * 4)

N = 10_000
EPS1 = EPS2 = 1.0
RHO = 0.5
ALPHA = 0.05
CHUNK = 2048


def _one_rep(key):
    xy = gen_gaussian(rng.stream(key, "dgp"), N, jnp.float32(RHO))
    r = ci_ni_signbatch(rng.stream(key, "ni"), xy[:, 0], xy[:, 1], EPS1, EPS2,
                        alpha=ALPHA)
    cover = ((RHO >= r.ci_low) & (RHO <= r.ci_high)).astype(jnp.float32)
    return (r.rho_hat - RHO) ** 2, cover, r.ci_high - r.ci_low


@partial(jax.jit, static_argnums=(1,))
def _run_block(key, n_reps: int):
    keys = rng.rep_keys(key, n_reps)
    se2, cover, ci_len = chunked_vmap(_one_rep, keys, CHUNK)
    return jnp.mean(se2), jnp.mean(cover), jnp.mean(ci_len)


TARGET_REPS = 512 * 1024


def _timed_run(key, n_reps):
    """Run + host-fetch the scalars. Fetch (not block_until_ready) is the
    only reliable completion barrier through the remote-TPU tunnel; its
    ~0.2 s RTT is amortized by the block size."""
    t0 = time.perf_counter()
    out = tuple(float(x) for x in _run_block(key, n_reps))
    return out, time.perf_counter() - t0


def main():
    key = rng.master_key()
    # warmup: compile the big block once
    _timed_run(rng.design_key(key, 0), TARGET_REPS)
    out, elapsed = _timed_run(rng.design_key(key, 1), TARGET_REPS)

    reps_per_sec = TARGET_REPS / elapsed
    mse, coverage, ci_len = (float(x) for x in out)
    print(json.dumps({
        "metric": "mc_reps_per_sec_chip_ni_sign_n10k",
        "value": round(reps_per_sec, 1),
        "unit": "reps/sec/chip",
        "vs_baseline": round(reps_per_sec / BASELINE_REPS_PER_SEC_CHIP, 3),
        "detail": {
            "n": N, "reps": TARGET_REPS, "seconds": round(elapsed, 2),
            "coverage": round(coverage, 4), "mse": round(mse, 6),
            "ci_length": round(ci_len, 4),
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
