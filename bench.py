"""Benchmark: MC replications/sec/chip on the north-star workload.

BASELINE.md: 1M Monte-Carlo reps of the Gaussian NI estimator at n=10k on a
TPU v4-8 (4 chips) in <60 s ⇒ baseline ≈ 1e6/(60·4) ≈ 4166.7 reps/sec/chip.
This script measures the same per-rep work — generate an n=10k correlated
Gaussian pair, privately standardize, sign-batch estimate + CI, emit metrics
(vert-cor.R:392-419) — and prints ONE JSON line.

Resilience (round-1 failure mode: TPU backend init hung and the whole bench
died with rc=1 and no number): the measurement runs in a *worker subprocess*
under a wall-clock timeout; the orchestrator process never initializes a JAX
backend itself. Sequence:

1. Bounded tunnel-health probe (one matmul in a throwaway process
   group). If it FAILS, both TPU attempts are skipped outright — the
   probe is the same program a worker would run first, so attempting
   anyway only buys two guaranteed timeouts — and the run degrades
   straight to CPU with ``degraded: "tpu-probe-failed"`` plus a relay
   snapshot (dead vs up-but-wedged) in the forensics.
2. TPU worker (full budget, long leash — the healthy probe proved the
   tunnel alive). On timeout/crash: one retry with a smaller budget (a
   slow first init sometimes succeeds the second time, cached).
3. CPU worker fallback, recorded with ``degraded: "tpu-init-failed"``.
4. If even that fails, a valid JSON line with value 0 and the error trail.

Exit code is 0 in every case (except ``--gate`` mode, below) — the driver
always receives a parseable measurement plus the failure forensics in
``detail``.

Inside a worker, two implementations are raced on TPU:

- **xla**: the framework's ``jit(vmap)`` estimator path (``dpcorr.sim``);
- **pallas**: the fused VMEM kernel (``dpcorr.ops.pallas_ni``) with on-chip
  hardware PRNG — TPU only; measured in its *own* bounded subprocess
  (a Mosaic compile hang has been observed to wedge the remote backend —
  isolation keeps the XLA number safe); any failure falls back to xla with
  the failure recorded in the JSON detail. **Opt-in** since r04
  (``DPCORR_BENCH_PALLAS=1``): three rounds of measurement put pallas at
  ≤0.98× xla on this workload (r02_grid_fused_tpu.json), and the r04
  session observed the tunnel wedge immediately after a killed 465 s
  Mosaic compile — an unattended driver run must not pay that risk for a
  path that has never held the headline. ``--worker tpu-pallas`` (the
  queue's explicit A/B) is unaffected.

Each path compiles one fixed-size block, calibrates its wall-clock, then
dispatches its share of the time budget asynchronously with a single fetch
barrier. The headline value is the faster path's steady-state reps/sec.

Since r08 the xla paths run through ``dpcorr.sim.RepBlockPipeline`` — the
donated, pre-sharded, chained-key block executor (bit-identical per-rep
math to the old ``make_xla_block`` loop, pinned by tests/test_pipeline.py
and the interleaved A/B in ``benchmarks/rep_pipeline_ab.py``) — with the
(chunk_size × block_reps) shape resolved by the per-host geometry
autotuner (``dpcorr.utils.geometry``; cached per device/family/n/dtype,
``DPCORR_BENCH_AUTOTUNE=0`` restores the measured constants). On CPU a
second sampler path ``xla_bm`` (Box–Muller, ``dpcorr.ops.fastnorm``)
races the threefry+erf⁻¹ path under the same ``_sane`` statistical gate
the rbg/pallas paths use. The worker stamps geometry, device_kind,
loadavg, the transfer-counter deltas and — where the backend exposes
memory introspection — per-device watermarks (``obs.devicemon``) into
``detail``.

``--gate`` turns the run into a CI regression gate: the measured value is
compared against ``benchmarks/results/last_known_good.json`` (same
device_kind only) and the process exits **1** below the floor
(``DPCORR_BENCH_GATE_FLOOR``, default 0.85) — the one deliberate
exception to the always-rc=0 contract above. ``--gate-measured FILE``
gates an existing artifact without measuring.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_REPS_PER_SEC_CHIP = 1_000_000 / (60.0 * 4)

N = 10_000
EPS1 = EPS2 = 1.0
RHO = 0.5
ALPHA = 0.05
MAX_BLOCKS = 32

# Per-platform knobs: (block_reps, vmap_chunk). The TPU shape is the
# measured sweet spot of the 2026-07-30 block-scaling sweep
# (benchmarks/results/r02_tpu_headline.json "block_scaling"): each block
# fetch pays ~0.2s of remote-tunnel latency, so small blocks measure the
# tunnel, not the chip — 2^19 reps/block reached 982k reps/sec (235x
# baseline) with stable coverage; 2^20 exceeded the worker timeout through
# the tunnel. Overridable for tuning runs without editing:
# DPCORR_BENCH_BLOCK_REPS / DPCORR_BENCH_CHUNK.
# The CPU fallback shape is measured-optimal too. 2026-07-31 sweep (the
# r04 streaming-width finding applied here: at n=10⁴ a 256-wide vmap
# chunk holds ~20 MB of live sample tables — far past L2): chunk 256 →
# 2283 reps/s, 64 → 2445, 32 → 2532; at chunk 32-64, block 8192 → 2577
# (2026-07-30's 2048/256 → 2282 baseline; bigger CHUNKS thrash CPU
# caches — the opposite of the TPU trend — while bigger BLOCKS amortize
# dispatch once the chunk fits).
WORKER_SHAPE = {"tpu": (512 * 1024, 16384), "cpu": (8192, 64)}


def _worker_shape(mode: str) -> tuple[int, int]:
    block_reps, chunk = WORKER_SHAPE["tpu" if mode == "tpu-pallas" else mode]
    if mode != "cpu":
        # overrides tune the TPU paths only — a TPU-sized block inherited
        # by the CPU fallback would blow through its kill timeout and cost
        # the degraded measurement the fallback exists to provide
        block_reps = int(os.environ.get("DPCORR_BENCH_BLOCK_REPS",
                                        block_reps))
        chunk = int(os.environ.get("DPCORR_BENCH_CHUNK", chunk))
    return block_reps, chunk

METRIC = "mc_reps_per_sec_chip_ni_sign_n10k"

#: committed regression baseline for --gate (same device_kind only)
LKG_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "results", "last_known_good.json")
#: a measurement below floor × last-known-good fails the gate
GATE_FLOOR_DEFAULT = 0.85


def make_metrics_fn():
    """Per-rep metrics (se², cover, ci_len) at the bench design point."""
    import jax.numpy as jnp

    def _metrics(r):
        cover = ((RHO >= r.ci_low) & (RHO <= r.ci_high)).astype(jnp.float32)
        return (r.rho_hat - RHO) ** 2, cover, r.ci_high - r.ci_low

    return _metrics


def make_xla_block(chunk: int):
    """The headline XLA kernel: (master key, n_reps) → mean metrics over
    n_reps replications of the north-star workload (generate n=10k
    Gaussian pair → NI sign-batch estimate + CI → metrics). Shared by the
    bench worker and the roofline instrumentation
    (``benchmarks/roofline.py``) so both always measure the same program.
    """
    from functools import partial

    import jax
    import jax.numpy as jnp

    from dpcorr.models.dgp import gen_gaussian
    from dpcorr.models.estimators import ci_ni_signbatch
    from dpcorr.sim import chunked_vmap
    from dpcorr.utils import rng

    _metrics = make_metrics_fn()

    def _one_rep(key):
        xy = gen_gaussian(rng.stream(key, "dgp"), N, jnp.float32(RHO))
        return _metrics(ci_ni_signbatch(rng.stream(key, "ni"),
                                        xy[:, 0], xy[:, 1],
                                        EPS1, EPS2, alpha=ALPHA))

    @partial(jax.jit, static_argnums=(1,))
    def _xla_block(key, n_reps: int):
        keys = rng.rep_keys(key, n_reps)
        se2, cover, ci_len = chunked_vmap(_one_rep, keys, chunk)
        return jnp.mean(se2), jnp.mean(cover), jnp.mean(ci_len)

    return _xla_block


def make_rep_fn(sampler: str = "icdf"):
    """Per-replication body of the headline workload: generate an n=10k
    correlated pair, NI sign-batch estimate + CI, emit (se², cover,
    ci_len). ``sampler`` picks the Gaussian generator: ``"icdf"`` is the
    framework's ``gen_gaussian`` (threefry + inverse CDF — the
    bit-reproducibility contract), ``"bm"`` the Box–Muller fast path
    (``dpcorr.ops.fastnorm`` — statistically exact, different stream;
    gated by ``_sane`` like rbg/pallas)."""
    import jax.numpy as jnp

    from dpcorr.models.estimators import ci_ni_signbatch
    from dpcorr.utils import rng

    if sampler == "bm":
        from dpcorr.ops.fastnorm import gen_gaussian_bm as gen
    elif sampler == "icdf":
        from dpcorr.models.dgp import gen_gaussian as gen
    else:
        raise ValueError(f"unknown sampler {sampler!r}")
    _metrics = make_metrics_fn()

    def _one_rep(key):
        xy = gen(rng.stream(key, "dgp"), N, jnp.float32(RHO))
        return _metrics(ci_ni_signbatch(rng.stream(key, "ni"),
                                        xy[:, 0], xy[:, 1],
                                        EPS1, EPS2, alpha=ALPHA))

    return _one_rep


def make_pipeline(chunk: int, block_reps: int, *, sampler: str = "icdf",
                  key=None, impl: str | None = None, counters=None,
                  aot: bool = True, profiler=None):
    """The donated rep-block executor over :func:`make_rep_fn` — what the
    worker measures since r08. ``impl``: PRNG impl for the key tree
    (``"rbg"`` for the TPU hardware generator path); the root ``key``
    must be built with the same impl. ``profiler``: an optional
    ``obs.prof.BlockProfiler`` (``DPCORR_PROF=...`` arms one for the
    whole worker via ``prof.active()``)."""
    from dpcorr.sim import RepBlockPipeline
    from dpcorr.utils import rng

    if key is None:
        key = rng.master_key(impl=impl)
    return RepBlockPipeline(make_rep_fn(sampler), 3, key=key,
                            block_reps=block_reps, chunk_size=chunk,
                            family=f"bench-{sampler}", impl=impl,
                            counters=counters, aot=aot, profiler=profiler)


def measure_pipeline(pipe, budget_s: float):
    """The steady-state protocol of :func:`measure_steady_state` on a
    :class:`~dpcorr.sim.RepBlockPipeline`: warm (compile excluded),
    calibrate one block's wall-clock, then run ~budget worth of chained
    blocks with the pipeline's single reduction-boundary fetch. Returns
    ``(reps_per_sec, mean metrics)``."""
    pipe.run(1, start_block=0)  # compile + warm
    t0 = time.perf_counter()
    pipe.run(1, start_block=1)
    dt1 = time.perf_counter() - t0
    n_blocks = max(1, min(MAX_BLOCKS, int(budget_s / dt1)))

    t0 = time.perf_counter()
    sums, n_reps = pipe.run(n_blocks, start_block=2)
    elapsed = time.perf_counter() - t0
    means = tuple(s / n_reps for s in sums)
    return n_reps / elapsed, means


def _resolve_geometry(mode: str, budget_s: float, key,
                      sampler: str = "icdf"):
    """Pick the (chunk_size × block_reps) shape for one worker path.

    CPU: the per-host autotuner (``dpcorr.utils.geometry``) when the
    budget affords a probe (≥ 10 s; the persistent cache makes this a
    one-time cost per host), else the cached winner, else the measured
    ``WORKER_SHAPE`` constant. The env pins are *ignored* here — they
    tune the TPU paths only (see ``_worker_shape``), and an inherited
    TPU-sized pin would blow the fallback's kill timeout.

    TPU: env pin or the measured constant; probing through the remote
    tunnel is opt-in (``DPCORR_BENCH_AUTOTUNE=1``) because a probe
    ladder costs minutes of tunnel exposure per entry.

    Each ``sampler`` tunes under its own cache family (``bench-icdf``,
    ``bench-bm``): the Box–Muller rep spends its cycles differently
    (no erf⁻¹), so the two paths need not share an optimum.
    """
    import itertools

    from dpcorr.utils import geometry

    family = f"bench-{sampler}"
    device_kind = "cpu" if mode == "cpu" else "tpu"
    opt = os.environ.get("DPCORR_BENCH_AUTOTUNE", "").strip().lower()
    forced = opt in ("1", "true", "on")
    disabled = opt in ("0", "off", "false")
    want_tune = forced or (device_kind == "cpu" and not disabled
                           and budget_s >= 10.0)
    if not want_tune:
        if device_kind == "cpu":
            if not disabled:
                geo = geometry.lookup(family, N, device_kind="cpu",
                                      eps_pairs=[(EPS1, EPS2)],
                                      env_pin=False)
                if geo is not None:
                    return geo
            block_reps, chunk = WORKER_SHAPE["cpu"]
            return geometry.Geometry(chunk_size=chunk,
                                     block_reps=block_reps,
                                     source="default")
        block_reps, chunk = _worker_shape(mode)
        pinned = (os.environ.get("DPCORR_BENCH_CHUNK") is not None
                  or os.environ.get("DPCORR_BENCH_BLOCK_REPS") is not None)
        return geometry.Geometry(chunk_size=chunk, block_reps=block_reps,
                                 source="pinned" if pinned else "default")

    def make_runner(c, b):
        pipe = make_pipeline(c, b, sampler=sampler, key=key, aot=False)
        idx = itertools.count()
        return lambda: pipe.run(1, start_block=next(idx))

    return geometry.autotune(family, N, make_runner,
                             device_kind=device_kind,
                             eps_pairs=[(EPS1, EPS2)],
                             env_pin=(device_kind == "tpu"))


def _path_entry(rps: float, means, pipe, geo=None) -> dict:
    entry = {"reps_per_sec": round(rps, 1), "mse": round(means[0], 6),
             "coverage": round(means[1], 4),
             "ci_length": round(means[2], 4),
             "donation_engaged": pipe.donation_engaged,
             "aot": pipe.aot_ok}
    if geo is not None:
        entry["geometry"] = geo.as_detail()
    return entry


def measure_steady_state(run_block, args_for, block_reps: int,
                         budget_s: float):
    """Compile, calibrate one block's wall-clock, then dispatch ~budget
    worth of blocks asynchronously and drain once (the host-fetch of the
    scalars is the only reliable completion barrier through the remote-TPU
    tunnel). Returns (reps_per_sec, mean metrics, per-block drain-latency
    percentiles). Shared by the bench workers and
    ``benchmarks/roofline.py`` so a measured reps/sec always means the
    same protocol. The percentile estimator is the serving layer's
    (dpcorr.serve.stats), so an offline p99 and the serve endpoint's p99
    are the same statistic — under dispatch-ahead, later blocks drain
    near-instantly, so a p99 far above p50 localizes tunnel stalls."""
    from dpcorr.serve.stats import percentiles

    def _fetch(out):
        return tuple(float(x) for x in out)

    _fetch(run_block(args_for(0), block_reps))  # compile + warm
    t0 = time.perf_counter()
    _fetch(run_block(args_for(1), block_reps))
    dt1 = time.perf_counter() - t0
    n_blocks = max(1, min(MAX_BLOCKS, int(budget_s / dt1)))

    t0 = time.perf_counter()
    futs = [run_block(args_for(2 + i), block_reps)
            for i in range(n_blocks)]
    outs, drains = [], []
    for f in futs:
        tb = time.perf_counter()
        outs.append(_fetch(f))
        drains.append(time.perf_counter() - tb)
    elapsed = time.perf_counter() - t0
    means = tuple(sum(o[j] for o in outs) / len(outs) for j in range(3))
    lat = {k: round(v, 4) for k, v in percentiles(drains).items()}
    return n_blocks * block_reps / elapsed, means, lat


# --------------------------------------------------------------------------
# Worker: the actual measurement. Runs in a subprocess; prints one JSON line.
# --------------------------------------------------------------------------

def worker_main(mode: str, budget_s: float) -> None:
    import jax

    # Persistent compile cache, ON by default at a stable per-user path:
    # doesn't change the measurement (the warm-up block already excludes
    # compile) but cuts minutes of tunnel exposure — and because XLA keys
    # entries by HLO hash, any earlier successful run (a queue step, a
    # manual bench) pre-warms the compile for the driver's unattended
    # round-end run even across git revisions. Per-user (not a fixed
    # world-shared /tmp name) so another account can neither collide with
    # nor pre-plant entries in it. DPCORR_COMPILE_CACHE=dir overrides the
    # path; =0/off/none disables. Parsing lives canonically in
    # dpcorr.utils.doctor (one rule, three consumers: bench default-ON,
    # dpcorr CLI opt-in, doctor's report of both).
    from dpcorr.utils.doctor import resolve_cache_dir

    cache_dir = resolve_cache_dir("bench")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    if mode == "cpu":
        # Must happen before any backend is initialized; keeps the worker
        # clear of the (possibly hung) TPU tunnel entirely.
        jax.config.update("jax_platforms", "cpu")
    elif jax.devices()[0].platform not in ("tpu", "axon"):  # tpu + tpu-pallas
        # Don't let a TPU-less host silently measure CPU with TPU-sized
        # blocks and report it as a healthy TPU number — fail loudly so the
        # orchestrator routes to the real CPU fallback (degraded-marked).
        raise RuntimeError(
            f"tpu worker got platform {jax.devices()[0].platform!r}")

    from functools import partial

    import jax.numpy as jnp

    from dpcorr.utils import rng

    block_reps, chunk = _worker_shape(mode)
    _metrics = make_metrics_fn()

    @partial(jax.jit, static_argnums=(1,))
    def _pallas_block(block_idx, n_reps: int):
        from dpcorr.ops.pallas_ni import ni_sign_pallas

        seeds = block_idx * n_reps + jnp.arange(n_reps, dtype=jnp.int32)
        r = ni_sign_pallas(seeds, RHO, N, EPS1, EPS2, alpha=ALPHA,
                           interpret=False,
                           gauss=os.environ.get("DPCORR_BENCH_PALLAS_GAUSS",
                                                "boxmuller"))
        se2, cover, ci_len = _metrics(r)
        return jnp.mean(se2), jnp.mean(cover), jnp.mean(ci_len)

    def _measure(run_block, args_for):
        return measure_steady_state(run_block, args_for, block_reps,
                                    budget_s)

    key = rng.master_key()

    if mode == "tpu-pallas":
        # Pallas-only worker — run by the orchestrator as a *sibling* of
        # the tpu worker, after it exits, so the two never contend for the
        # (possibly exclusive) TPU client; a Mosaic compile hang here kills
        # only this process, never the already-captured XLA number.
        p_rps, p_means, p_lat = _measure(_pallas_block, lambda i: jnp.int32(i))
        print(json.dumps({
            "metric": METRIC, "value": round(p_rps, 1),
            "unit": "reps/sec/chip",
            "vs_baseline": round(p_rps / BASELINE_REPS_PER_SEC_CHIP, 3),
            "detail": {"paths": {"pallas": {
                "reps_per_sec": round(p_rps, 1),
                "mse": round(p_means[0], 6),
                "coverage": round(p_means[1], 4),
                "ci_length": round(p_means[2], 4),
                "block_drain_s": p_lat}}},
        }), flush=True)
        return

    # ---- xla paths: the donated rep-block pipeline (r08 tentpole) ----
    from dpcorr.obs import transfer as transfer_mod

    counters = transfer_mod.default_counters()
    geo = _resolve_geometry(mode, budget_s, key)
    bm_geo = (_resolve_geometry(mode, budget_s, key, sampler="bm")
              if mode == "cpu" else None)
    before = counters.snapshot()  # after the probes: the measurement's own

    from dpcorr.obs import prof as prof_mod

    profiler = prof_mod.active()  # armed only via DPCORR_PROF
    pipe = make_pipeline(geo.chunk_size, geo.block_reps, key=key,
                         counters=counters, profiler=profiler)
    xla_rps, xla_means = measure_pipeline(pipe, budget_s)
    paths = {"xla": _path_entry(xla_rps, xla_means, pipe, geo)}
    geos = {"xla": geo}
    pipes = {"xla": pipe}

    if mode == "tpu":
        # Same kernel on the rbg key impl (the TPU hardware generator):
        # the threefry key derivation dominates the XLA path's runtime, so
        # this is the cheap-PRNG variant. Gated on the same statistical
        # sanity as pallas — different streams, same distributions.
        try:
            rbg_pipe = make_pipeline(geo.chunk_size, geo.block_reps,
                                     impl="rbg", counters=counters)
            rbg_rps, rbg_means = measure_pipeline(rbg_pipe, budget_s)
            if _sane(rbg_means, xla_means):
                paths["xla_rbg"] = _path_entry(rbg_rps, rbg_means,
                                               rbg_pipe, geo)
                geos["xla_rbg"] = geo
                pipes["xla_rbg"] = rbg_pipe
            else:
                paths["xla_rbg_skipped"] = f"sanity: {rbg_means}"
        except Exception as e:
            paths["xla_rbg_skipped"] = f"{type(e).__name__}: {e}"[:200]
    else:
        # CPU fast path: Box–Muller sampler (no erf⁻¹ — XLA CPU
        # scalarizes the inverse-CDF's log1p into per-element libm
        # calls; dpcorr.ops.fastnorm). Different stream, same law:
        # gated statistically, stamped as its own path, tuned under its
        # own geometry family (the rep spends its cycles differently).
        try:
            bm_pipe = make_pipeline(bm_geo.chunk_size, bm_geo.block_reps,
                                    sampler="bm", key=key,
                                    counters=counters)
            bm_rps, bm_means = measure_pipeline(bm_pipe, budget_s)
            if _sane(bm_means, xla_means):
                paths["xla_bm"] = _path_entry(bm_rps, bm_means, bm_pipe,
                                              bm_geo)
                geos["xla_bm"] = bm_geo
                pipes["xla_bm"] = bm_pipe
            else:
                paths["xla_bm_skipped"] = f"sanity: {bm_means}"
        except Exception as e:
            paths["xla_bm_skipped"] = f"{type(e).__name__}: {e}"[:200]

    best = max((p for p in paths if not p.endswith("_skipped")),
               key=lambda p: paths[p]["reps_per_sec"])
    best_geo = geos[best]
    try:
        loadavg_1m = round(os.getloadavg()[0], 2)
    except OSError:
        loadavg_1m = None
    platform = jax.devices()[0].platform
    detail = {
        "n": N, "block_reps": best_geo.block_reps,
        "chunk_size": best_geo.chunk_size,
        "path": best, "paths": paths,
        "device": str(jax.devices()[0]),
        "device_kind": "tpu" if platform in ("tpu", "axon") else platform,
        # devices the measurement actually ran on (the winning
        # pipeline's placement, not the host inventory) + mesh shape, so
        # trajectory/gate attribution never folds a 1-device series with
        # an N-device sharded one
        "device_count": pipes[best].placement.device_count,
        "geometry": best_geo.as_detail(),
        "transfer": transfer_mod.diff(counters.snapshot(), before),
    }
    mesh_shape = pipes[best].placement.mesh_shape()
    if mesh_shape:
        detail["mesh"] = mesh_shape
    # measured arithmetic intensity (ISSUE 15): the winning kernel's XLA
    # cost analysis, per-rep normalized — benchmarks/roofline.py consumes
    # this instead of hand-derived FLOP constants
    cost = pipes[best].cost_summary()
    if cost:
        detail["cost_analysis"] = cost
    # per-device memory watermarks (ISSUE 11): absent — not zero — when
    # the backend exposes no introspection (CPU allocators usually don't)
    from dpcorr.obs import devicemon

    device_wm = devicemon.watermarks_detail(transfer_counters=counters)
    if any(device_wm.values()):
        detail["devices"] = device_wm
    if loadavg_1m is not None:
        detail["loadavg_1m"] = loadavg_1m
    print(json.dumps({
        "metric": METRIC,
        "value": paths[best]["reps_per_sec"],
        "unit": "reps/sec/chip",
        "vs_baseline": round(paths[best]["reps_per_sec"]
                             / BASELINE_REPS_PER_SEC_CHIP, 3),
        "detail": detail,
    }), flush=True)


def _sane(means, ref_means) -> bool:
    """Pallas draws from a different PRNG, so agreement with the XLA path
    is statistical: coverage near nominal, mse/ci_length within 30% of the
    XLA-measured values."""
    mse, coverage, ci_len = means
    ref_mse, _, ref_ci_len = ref_means
    return (0.90 <= coverage <= 0.99
            and 0.7 * ref_mse < mse < 1.3 * ref_mse
            and 0.7 * ref_ci_len < ci_len < 1.3 * ref_ci_len)


def _merge_pallas(out: dict, budget_s: float) -> None:
    """Run the pallas worker (its own process + TPU client) and fold its
    result into the tpu worker's measurement, keeping the faster path."""
    if os.environ.get("DPCORR_BENCH_PALLAS", "").lower() in ("", "0", "false"):
        out["detail"]["pallas_skipped"] = (
            "not attempted (opt in: DPCORR_BENCH_PALLAS=1); measured <=0.98x "
            "xla r02-r03 and a killed Mosaic compile is the leading "
            "tunnel-wedge suspect (STATUS_r04.md)")
        return
    p_out, p_err = _run_worker("tpu-pallas",
                               timeout_s=420 + 1.5 * budget_s,
                               budget_s=budget_s)
    if p_out is None:
        out["detail"]["pallas_skipped"] = p_err
        return
    p = p_out["detail"]["paths"]["pallas"]
    xla = out["detail"]["paths"]["xla"]
    if not _sane((p["mse"], p["coverage"], p["ci_length"]),
                 (xla["mse"], xla["coverage"], xla["ci_length"])):
        out["detail"]["pallas_skipped"] = f"sanity check failed: {p}"
        return
    out["detail"]["paths"]["pallas"] = p
    if p["reps_per_sec"] > out["value"]:
        out["value"] = p["reps_per_sec"]
        out["vs_baseline"] = round(p["reps_per_sec"]
                                   / BASELINE_REPS_PER_SEC_CHIP, 3)
        out["detail"]["path"] = "pallas"


# --------------------------------------------------------------------------
# Regression gate (--gate): measured value vs the committed last-known-good.
# --------------------------------------------------------------------------

def _gate_floor() -> float:
    raw = os.environ.get("DPCORR_BENCH_GATE_FLOOR", "")
    try:
        return float(raw)
    except ValueError:
        return GATE_FLOOR_DEFAULT


def _load_lkg(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            lkg = json.load(f)
        return lkg if isinstance(lkg, dict) else None
    except (OSError, ValueError):
        return None


def gate_check(measured: dict, lkg: dict | None, floor: float
               ) -> tuple[bool, str]:
    """Pure regression verdict: ``(ok, reason)``.

    Fails (ok=False) only when the measured value is below
    ``floor × lkg.value`` *on the same device_kind* — a CPU-degraded run
    must not be judged against a TPU baseline (or vice versa): the gate
    passes with a note instead, so a dead tunnel degrades the
    measurement without turning CI red for an unrelated reason. A
    missing/unreadable baseline also passes (first run bootstraps the
    file). A measurement whose own device_kind is missing is still
    compared — the all-paths-failed zero artifact must fail, not slip
    through on a missing stamp.
    """
    value = float(measured.get("value") or 0.0)
    if lkg is None:
        return True, "no last-known-good baseline; gate passes (bootstrap)"
    if lkg.get("metric") not in (None, METRIC):
        return True, (f"baseline tracks {lkg.get('metric')!r}, not "
                      f"{METRIC!r}; gate passes with note")
    lkg_value = float(lkg.get("value") or 0.0)
    if lkg_value <= 0:
        return True, "baseline value is unusable (<= 0); gate passes"
    m_kind = (measured.get("detail") or {}).get("device_kind")
    l_kind = lkg.get("device_kind")
    if m_kind and l_kind and m_kind != l_kind:
        return True, (f"device_kind mismatch (measured {m_kind}, baseline "
                      f"{l_kind}); cross-device ratios are meaningless — "
                      "gate passes with note")
    ratio = value / lkg_value
    verdict = (f"{value:.1f} vs last-known-good {lkg_value:.1f} "
               f"({ratio:.3f}x, floor {floor:.2f}x"
               + (f", device_kind {l_kind}" if l_kind else "") + ")")
    if ratio >= floor:
        return True, verdict
    return False, f"REGRESSION: {verdict}"


# --------------------------------------------------------------------------
# Orchestrator: bounded-time worker attempts, guaranteed rc=0 + JSON.
# --------------------------------------------------------------------------

def _reap(p) -> None:
    """Kill a worker's whole process group and wait for it.

    Must never block the orchestrator forever: if the group kill is
    refused (PermissionError), fall back to killing the direct child, and
    bound the wait — a reap that cannot finish should not turn a degrade
    path into a hang.
    """
    import signal

    try:
        os.killpg(p.pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    except PermissionError:
        p.kill()
    try:
        p.wait(timeout=10)
    except subprocess.TimeoutExpired:
        pass


def _run_worker(mode: str, timeout_s: float, budget_s: float):
    """Spawn a worker; return (parsed JSON, None) or (None, error string).

    Workers get their own process group and the whole group is killed on
    timeout, so nothing a hung worker leaves behind (helper threads,
    library-spawned children) can keep the exclusive TPU client alive and
    wedge the next attempt. The tpu-pallas probe runs as a *sibling*
    worker via this same path after the tpu worker exits (see
    ``_merge_pallas``), never nested inside it.

    The worker must die with the orchestrator, too: an r04 session caught
    an externally SIGTERM'd orchestrator (a queue step `timeout`) leaving
    its detached worker alive for 13+ minutes, holding the exclusive TPU
    client — i.e. exactly the mid-queue wedge the markers blame on the
    tunnel. ``main`` converts SIGTERM into SystemExit so the ``finally``
    here reaps the group on every exit path short of SIGKILL.
    """
    cmd = [sys.executable, os.path.abspath(__file__),
           "--worker", mode, "--budget", str(budget_s)]
    try:
        p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             start_new_session=True)
    except Exception as e:  # spawn failure itself
        return None, f"{mode} worker: {type(e).__name__}: {e}"[:300]
    try:
        try:
            stdout, stderr = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            # the finally below reaps before this return completes; no
            # explicit _reap here or an unkillable worker doubles the wait
            return None, f"{mode} worker: timeout after {timeout_s:.0f}s"
    finally:
        # reaps on SIGTERM-as-SystemExit, KeyboardInterrupt, or any bug in
        # the orchestrator itself — not just the worker's own timeout
        if p.poll() is None:
            _reap(p)
    if p.returncode != 0:
        tail = (stderr or "").strip().splitlines()[-3:]
        return None, (f"{mode} worker: rc={p.returncode}: "
                      + " | ".join(tail))[:400]
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            out = json.loads(line)
        except json.JSONDecodeError:
            continue
        # only accept the measurement line, not stray JSON-parseable tokens
        if isinstance(out, dict) and out.get("metric") == METRIC:
            return out, None
    return None, f"{mode} worker: exited 0 but printed no measurement JSON"


#: the probe payload: one matmul on the default backend, which must be a
#: real TPU — a silent CPU fallback is NOT healthy and must exit nonzero
PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "assert jax.devices()[0].platform in ('tpu', 'axon'), "
    "jax.devices()[0].platform; "
    "print(float((jnp.ones((128,128))@jnp.ones((128,128))).sum()))")


def _sweep_stranded_clients() -> list:
    """Kill bench workers orphaned by an earlier uncatchable orchestrator
    death (reparented to init). Such a worker holds the exclusive TPU
    client and makes a healthy tunnel probe as dead — observed live in
    r04, where one stranded worker read as a 13-minute tunnel wedge.
    Running it before the health probe makes the driver's unattended
    round-end run self-healing. Returns the swept pids (for the JSON
    forensics). The match rule lives canonically in
    ``dpcorr.utils.doctor`` (``benchmarks/tpu_r05_queue.sh`` mirrors it
    in shell); keeping one Python implementation stops the three copies
    drifting apart."""
    from dpcorr.utils.doctor import find_stray_workers, sweep_strays

    return sweep_strays(find_stray_workers())


def _health_probe(timeout_s: float = 150.0) -> bool:
    """Bounded TPU-liveness probe in a throwaway process group (the same
    one-matmul check ``benchmarks/tpu_revalidate.sh`` polls with). Its
    verdict picks the first tpu worker's leash: a probe that *succeeds*
    proves the tunnel is alive, so a slow init/compile afterwards deserves
    patience rather than a kill (the r02 round lost its headline to two
    worker timeouts on a tunnel that was merely slow); a probe that fails
    keeps the short timeout so a wedged tunnel degrades to CPU quickly."""
    p = None
    try:
        p = subprocess.Popen([sys.executable, "-c", PROBE_CODE],
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL,
                             start_new_session=True)
        p.communicate(timeout=timeout_s)
        return p.returncode == 0
    except Exception:
        return False
    finally:
        # every failure path (not just TimeoutExpired) must reap the probe
        # process group, or a leaked child keeps the TPU tunnel handle the
        # probe exists to quarantine
        if p is not None and p.poll() is None:
            _reap(p)


def main() -> None:
    # An external SIGTERM (queue step `timeout`, driver cleanup) must not
    # strand a detached worker holding the exclusive TPU client: convert
    # it to SystemExit so _run_worker's finally reaps the group.
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", choices=["tpu", "tpu-pallas", "cpu"],
                    default=None)
    ap.add_argument("--budget", type=float, default=30.0,
                    help="per-path measurement budget (seconds)")
    ap.add_argument("--gate", action="store_true",
                    help="compare the measurement against the committed "
                         "last-known-good baseline and exit 1 on "
                         "regression (the one non-rc=0 mode)")
    ap.add_argument("--gate-measured", type=str, default=None,
                    help="gate an existing bench JSON artifact instead "
                         "of measuring (implies --gate)")
    ap.add_argument("--lkg", type=str, default=LKG_PATH,
                    help="last-known-good baseline path")
    args = ap.parse_args()

    if args.worker:
        worker_main(args.worker, args.budget)
        return

    # Orchestrator only — a worker must keep SIG_DFL so a direct SIGTERM
    # still kills it even when it's wedged inside a native Mosaic compile
    # (a Python-level handler can't run while C code holds the GIL).
    import signal

    def _sigterm_to_exit(signum, frame):
        # latch: ignore further SIGTERMs so a second one cannot abort the
        # finally-block reap in _run_worker and strand the worker anyway
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        raise SystemExit(128 + signum)

    signal.signal(signal.SIGTERM, _sigterm_to_exit)

    if args.gate or args.gate_measured:
        if args.gate_measured:
            with open(args.gate_measured, encoding="utf-8") as f:
                measured = json.load(f)
        else:
            measured = _orchestrate(args)
        floor = _gate_floor()
        lkg = _load_lkg(args.lkg)
        ok, reason = gate_check(measured, lkg, floor)
        gate = {
            "ok": ok, "reason": reason, "floor": floor,
            "lkg_value": (lkg or {}).get("value"),
            "lkg_path": args.lkg,
        }
        if not ok:
            # trajectory attribution (ISSUE 15): name the FIRST artifact
            # in the committed series that bent the curve, not just the
            # bare ratio. Jax-free and best-effort — attribution may be
            # None (cold history) but must never change the verdict.
            try:
                from dpcorr.obs import trajectory as traj_mod

                root = os.path.dirname(os.path.abspath(__file__))
                first = traj_mod.gate_attribution(
                    traj_mod.default_roots(root),
                    metric=str(measured.get("metric") or METRIC),
                    device_kind=str((measured.get("detail") or {})
                                    .get("device_kind") or "unknown"),
                    measured_value=float(measured.get("value") or 0.0),
                    floor=floor)
            except Exception:
                first = None
            if first is not None:
                gate["first_regression"] = first
                reason += (f"; first regressing artifact: {first['path']}"
                           f" ({first['ratio']:.2f}x of best"
                           f" {first['best_path']})")
                gate["reason"] = reason
        measured.setdefault("detail", {})["gate"] = gate
        print(json.dumps(measured), flush=True)
        sys.exit(0 if ok else 1)

    out = _orchestrate(args)
    print(json.dumps(out), flush=True)
    sys.exit(0)


def _orchestrate(args) -> dict:
    """The resilience ladder (probe → tpu → retry → cpu → zero-value):
    always returns a parseable measurement dict; never raises for a
    worker failure."""
    attempts = []
    try:
        # CPU contention forensics, sampled BEFORE the bench's own
        # workers run (they saturate the 1-core box themselves and would
        # mask external load): a competing niced job halves the CPU
        # fallback's measured rate (the r04 degraded artifact's 1,234.7
        # vs 2,577 clean); the 1-minute load average at bench start
        # makes that attributable from the artifact alone.
        loadavg_start = round(os.getloadavg()[0], 2)
    except OSError:
        loadavg_start = None
    swept = _sweep_stranded_clients()
    healthy = _health_probe()
    relay_state = None
    out = err = None
    if not healthy:
        # Snapshot the relay endpoint NOW, not at artifact-write time —
        # the cpu fallback below can take minutes, and an infra redial
        # in that window would otherwise misattribute the probe failure
        # (dead endpoint vs endpoint-up-but-chip-wedged, STATUS_r04.md).
        try:
            from dpcorr.utils.doctor import check_relay

            relay_state = "up" if check_relay()["alive"] else "dead"
        except Exception:
            pass
        # A failed probe skips the TPU attempts entirely and degrades
        # straight to CPU. The probe is the same one-matmul program a
        # worker would run first — if IT can't finish in 150 s, a real
        # worker won't either, and the old shortened-leash ladder still
        # paid 420 s + 270 s (or 200 s + 270 s on connection-refused,
        # the two leashes measured 495 s + 295 s in the STATUS_r04
        # dead-endpoint rehearsal) of guaranteed timeout before the
        # number the round was always going to report. The skip is
        # recorded in the attempt trail and the relay snapshot keeps
        # the dead-vs-wedged forensics the ladder used to encode.
        attempts.append("tpu worker: skipped (health probe failed"
                        + (f", relay {relay_state}" if relay_state else "")
                        + ")")
    else:
        # Attempt 1: TPU, full budget, XLA path only. Init alone can
        # take minutes through the tunnel; the timeout bounds init +
        # compile + the measurement and scales with the requested budget
        # so a long --budget isn't killed mid-measurement. The healthy
        # probe bought the long leash: the tunnel is alive, so a timeout
        # here would only kill a slow-but-working run (the r02 round
        # lost its headline exactly that way).
        out, err = _run_worker("tpu", timeout_s=900 + 2.5 * args.budget,
                               budget_s=args.budget)
        if out is None:
            attempts.append(err)
            # Retry once, smaller budget — a compile cache or
            # late-arriving backend sometimes makes the second attempt
            # succeed.
            retry_budget = min(10.0, args.budget)
            out, err = _run_worker("tpu",
                                   timeout_s=270 + 2.5 * retry_budget,
                                   budget_s=retry_budget)
        if out is not None:
            # Pallas probe as a *sibling* worker after the tpu worker
            # exited (own TPU client; a Mosaic hang loses only this
            # probe).
            _merge_pallas(out, args.budget)
        else:
            attempts.append(err)
    if out is None:
        # Full budget, not a 10 s stub: the degraded artifact is the
        # round's official number when the tunnel is dead, and r04's
        # 10 s fallback measured only ~3 blocks — too few to amortize
        # per-block dispatch, and hypersensitive to transient load on
        # this 1-core box (BENCH_r04: 1,234.7 vs the clean-box 2,577
        # sweep value, with a niced 4 h job sharing the core). The
        # extra wall cost is bounded (~2.5x budget) and only paid on
        # the already-slow degrade path.
        out, err = _run_worker("cpu", timeout_s=200 + 2.5 * args.budget,
                               budget_s=args.budget)
        if out is not None:
            # two distinct degrade markers: "tpu-probe-failed" (never
            # attempted — the probe said no) vs "tpu-init-failed" (both
            # real attempts ran and died)
            out["detail"]["degraded"] = ("tpu-init-failed" if healthy
                                         else "tpu-probe-failed")
            here = os.path.dirname(os.path.abspath(__file__))
            for evidence_rel in ("benchmarks/results/r05_tpu_headline.json",
                                 "benchmarks/results/r04_tpu_headline.json",
                                 "benchmarks/results/r03_tpu_headline.json",
                                 "benchmarks/results/r02_tpu_headline.json"):
                if os.path.exists(os.path.join(here,
                                               *evidence_rel.split("/"))):
                    # point the consumer at the newest healthy-chip
                    # measurement on record (repo-relative; the file
                    # carries its own capture date/config — it documents
                    # what the chip did then, not a remeasurement of the
                    # current revision)
                    out["detail"]["recorded_tpu_evidence"] = evidence_rel
                    break
    if out is None:
        attempts.append(err)
        out = {"metric": METRIC, "value": 0.0, "unit": "reps/sec/chip",
               "vs_baseline": 0.0,
               "detail": {"degraded": "all-paths-failed"}}
    if attempts:
        out.setdefault("detail", {})["attempts"] = attempts
    out.setdefault("detail", {})["tunnel_health_probe"] = (
        "ok" if healthy else "failed")
    if relay_state is not None:
        out["detail"]["relay_endpoint"] = relay_state
    if swept:
        out["detail"]["swept_stranded_clients"] = swept
    if loadavg_start is not None:
        out["detail"]["loadavg_1m_at_start"] = loadavg_start
    try:  # provenance: which revision this measurement describes
        rev = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10).stdout.strip()
        if rev:
            out["detail"]["git_rev"] = rev
    except Exception:
        pass
    _stamp_metrics_snapshot(out)
    return out


def _stamp_metrics_snapshot(out: dict) -> None:
    """Publish the headline through the obs metrics registry and stamp
    the snapshot into the artifact, so the bench speaks the same metric
    dialect as the server: a dashboard scraping ``dpcorr_*`` series and
    a human reading the JSON see the same numbers. The degrade ladder's
    outcome — healthy, ``tpu-probe-failed`` (never attempted),
    ``tpu-init-failed`` (attempted and died), ``all-paths-failed`` —
    becomes a labeled counter instead of a string only greppable out of
    ``detail``."""
    try:
        from dpcorr.obs.metrics import Registry
    except Exception:
        return  # the artifact must survive a broken obs import
    reg = Registry()
    reg.gauge("dpcorr_bench_headline_reps_per_sec_chip",
              "bench headline throughput (reps/sec/chip)",
              ).set(float(out.get("value", 0.0)))
    reg.gauge("dpcorr_bench_vs_baseline_ratio",
              "headline / committed interactive baseline",
              ).set(float(out.get("vs_baseline", 0.0)))
    degraded = out.get("detail", {}).get("degraded")
    g = reg.gauge("dpcorr_bench_degraded",
                  "1 when the headline came from a degraded path",
                  labelnames=("reason",))
    g.set(1.0 if degraded else 0.0, reason=degraded or "none")
    c = reg.counter("dpcorr_bench_tpu_probe_failures_total",
                    "degrade-ladder outcomes by failure reason",
                    labelnames=("reason",))
    if degraded:
        c.inc(reason=degraded)
    values = {}
    for m in reg.metrics():
        for name, labels, value in m.samples():
            values[f"{name}{labels}"] = value
    out.setdefault("detail", {})["metrics"] = {
        "values": values,
        "exposition": reg.render(),
    }


if __name__ == "__main__":
    main()
