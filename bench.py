"""Benchmark: MC replications/sec/chip on the north-star workload.

BASELINE.md: 1M Monte-Carlo reps of the Gaussian NI estimator at n=10k on a
TPU v4-8 (4 chips) in <60 s ⇒ baseline ≈ 1e6/(60·4) ≈ 4166.7 reps/sec/chip.
This script measures the same per-rep work — generate an n=10k correlated
Gaussian pair, privately standardize, sign-batch estimate + CI, emit metrics
— on whatever single chip is available, and prints ONE JSON line.

One fixed-size block is compiled once, then run with fresh keys until the
time budget is spent — so total wall-clock is bounded (~compile + budget)
on any chip speed, while the measurement still amortizes dispatch overhead.
"""

from __future__ import annotations

import json
import time
from functools import partial

import jax
import jax.numpy as jnp

from dpcorr.models.estimators import ci_ni_signbatch
from dpcorr.models.dgp import gen_gaussian
from dpcorr.sim import chunked_vmap
from dpcorr.utils import rng

BASELINE_REPS_PER_SEC_CHIP = 1_000_000 / (60.0 * 4)

N = 10_000
EPS1 = EPS2 = 1.0
RHO = 0.5
ALPHA = 0.05
CHUNK = 2048
BLOCK_REPS = 32 * 1024
TIME_BUDGET_S = 60.0
MAX_BLOCKS = 32


def _one_rep(key):
    xy = gen_gaussian(rng.stream(key, "dgp"), N, jnp.float32(RHO))
    r = ci_ni_signbatch(rng.stream(key, "ni"), xy[:, 0], xy[:, 1], EPS1, EPS2,
                        alpha=ALPHA)
    cover = ((RHO >= r.ci_low) & (RHO <= r.ci_high)).astype(jnp.float32)
    return (r.rho_hat - RHO) ** 2, cover, r.ci_high - r.ci_low


@partial(jax.jit, static_argnums=(1,))
def _run_block(key, n_reps: int):
    keys = rng.rep_keys(key, n_reps)
    se2, cover, ci_len = chunked_vmap(_one_rep, keys, CHUNK)
    return jnp.mean(se2), jnp.mean(cover), jnp.mean(ci_len)


def _timed_run(key, n_reps):
    """Run + host-fetch the scalars. Fetch (not block_until_ready) is the
    only reliable completion barrier through the remote-TPU tunnel."""
    t0 = time.perf_counter()
    out = tuple(float(x) for x in _run_block(key, n_reps))
    return out, time.perf_counter() - t0


def main():
    key = rng.master_key()
    # warmup: compile the block once
    _timed_run(rng.design_key(key, 0), BLOCK_REPS)
    # calibrate block wall-clock, then dispatch the whole budget with a
    # single fetch barrier at the end — the per-fetch tunnel RTT is paid
    # once, not per block
    _, dt1 = _timed_run(rng.design_key(key, 1), BLOCK_REPS)
    n_blocks = max(1, min(MAX_BLOCKS, int(TIME_BUDGET_S / dt1)))

    t0 = time.perf_counter()
    futs = [_run_block(rng.design_key(key, 2 + i), BLOCK_REPS)
            for i in range(n_blocks)]  # async dispatch
    outs = [tuple(float(x) for x in f) for f in futs]  # one drain
    elapsed = time.perf_counter() - t0
    reps = n_blocks * BLOCK_REPS

    reps_per_sec = reps / elapsed
    mse, coverage, ci_len = (sum(o[j] for o in outs) / len(outs)
                             for j in range(3))
    print(json.dumps({
        "metric": "mc_reps_per_sec_chip_ni_sign_n10k",
        "value": round(reps_per_sec, 1),
        "unit": "reps/sec/chip",
        "vs_baseline": round(reps_per_sec / BASELINE_REPS_PER_SEC_CHIP, 3),
        "detail": {
            "n": N, "reps": reps, "seconds": round(elapsed, 2),
            "coverage": round(coverage, 4), "mse": round(mse, 6),
            "ci_length": round(ci_len, 4),
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
